"""ICT007/ICT008: static race detection for ``service/``, ``obs/``, and
``fleet/``.

The serving daemon runs five-plus concurrent threads (loaders, tick,
dispatch worker, shadow auditor, HTTP request threads) — and the fleet
router adds its poll loop plus its own HTTP request threads — over shared state
that lives in two shapes: module globals (the obs registries) and
attributes of lock-owning classes (scheduler buckets, the job index).
This detector makes the locking discipline *checkable*:

- **Catalog** — module-level mutable state (mutable-literal initializers,
  or any name rebound via ``global`` from a function) and, in *concurrent
  classes* (classes that construct a ``threading.Lock``/``RLock`` in
  ``__init__`` or subclass ``threading.Thread``), instance attributes
  mutated from two or more non-``__init__`` methods (the multi-writer
  heuristic: a single post-init writer is the common benign
  single-owner pattern and stays out of scope).
- **ICT007/guarded-by** — every cataloged item must carry an
  ``# ict: guarded-by(<lock>)`` annotation on its defining assignment:
  either a lock declared in the same scope (module global or ``self.``
  attribute) or ``none: <reason>`` for deliberately lock-free state
  (GIL-atomic idempotent caches, pre-thread startup writes).  For
  lock-annotated state, every mutation site must sit lexically inside a
  ``with <lock>:`` block — an unannotated or outside-the-lock write is
  exactly the class of bug the drain-manifest race was (CHANGES.md PR 5).
  When every observed mutation already sits under one consistent lock,
  the finding carries a mechanical ``--fix`` (append the annotation).
- **ICT008/lock-order** — the acquisition graph (edges: lock B acquired
  — lexically or via a resolvable same-package call chain — while lock A
  is held) must be acyclic; a cycle is a potential deadlock even if
  today's schedules never interleave it.

Static, lexical, and deliberately conservative: reads are not enforced
(snapshot-read-under-lock is a convention the annotations document, not
one AST analysis can prove), calls are resolved only within the analyzed
package (same module, same class, or an imported analyzed module), and
``queue.Queue``/``threading.Event``/contextvars are treated as
internally synchronized.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from iterative_cleaner_tpu.analysis.engine import Finding, SourceFile
from iterative_cleaner_tpu.analysis.rules import dotted_name

#: The packages the detector walks (repo-relative prefixes).  The
#: fleet/ prefix covers the whole elastic tier — router, registry,
#: tenants, obs, and (ISSUE 11) the capacity model and autoscaler
#: (fleet/capacity.py, fleet/autoscale.py), whose locks sit strictly
#: after the router's in the acquisition order.
RACE_SCOPE_PREFIXES = (
    "iterative_cleaner_tpu/service/",
    "iterative_cleaner_tpu/obs/",
    "iterative_cleaner_tpu/fleet/",
    # ISSUE 16: the campaign orchestrator's tables and the spool store —
    # its lock orders after the router's (campaign/orchestrator.py).
    "iterative_cleaner_tpu/campaign/",
    # ISSUE 17: the proving ground — the soak driver and chaos drills
    # are single-threaded by design (they DRIVE the router's tick), so
    # their state is annotated thread-confined rather than locked.
    "iterative_cleaner_tpu/proving/",
)

LOCK_FACTORIES = {"Lock", "RLock"}
#: Internally-synchronized (or thread-confined) constructs — exempt state.
SYNCHRONIZED_FACTORIES = {
    "Event", "Condition", "Semaphore", "BoundedSemaphore", "Barrier",
    "local", "Timer", "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "ContextVar", "compile", "object",
}
MUTABLE_FACTORIES = {
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter", "bytearray",
}
MUTATOR_METHODS = {
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "remove", "discard", "clear", "insert", "extend", "extendleft",
    "setdefault", "sort", "rotate",
}


@dataclass
class ModuleModel:
    sf: SourceFile
    modname: str                                  # e.g. "obs.flight"
    locks: set[str] = field(default_factory=set)  # module-level lock names
    import_aliases: dict[str, str] = field(default_factory=dict)
    # class name -> set of "self.X" lock attr names (X only)
    class_locks: dict[str, set[str]] = field(default_factory=dict)
    concurrent_classes: set[str] = field(default_factory=set)
    # candidate global name -> defining lineno
    global_candidates: dict[str, int] = field(default_factory=dict)
    # (class, attr) -> defining lineno in __init__
    attr_candidates: dict[tuple[str, str], int] = field(default_factory=dict)
    # (class, attr) -> sorted writer method names (non-init)
    attr_writers: dict[tuple[str, str], set[str]] = field(default_factory=dict)


def _is_constant_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):  # tuple-of-constants CONFIG
        return all(_is_constant_expr(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_constant_expr(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_constant_expr(node.left) and _is_constant_expr(node.right)
    return False


def _factory_of(node: ast.AST) -> str | None:
    """Trailing callable name of a Call initializer ('Lock' for
    threading.Lock(), 'deque' for collections.deque(...))."""
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name:
            return name.split(".")[-1]
    return None


def _is_mutable_init(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    factory = _factory_of(node)
    return factory in MUTABLE_FACTORIES


# --- per-module cataloging ---


def _module_name(path: str) -> str:
    # iterative_cleaner_tpu/obs/flight.py -> obs.flight
    parts = path.replace(".py", "").split("/")
    return ".".join(parts[1:]) if len(parts) > 1 else parts[0]


def build_model(sf: SourceFile) -> ModuleModel:
    tree = sf.tree
    model = ModuleModel(sf=sf, modname=_module_name(sf.path))
    assert isinstance(tree, ast.Module)

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                model.import_aliases[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                model.import_aliases[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}")

    # Module-level assignments.
    for stmt in tree.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = [t for t in stmt.targets if isinstance(t, ast.Name)]
            value = stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target] if isinstance(stmt.target, ast.Name) else []
            value = stmt.value
        else:
            continue
        factory = _factory_of(value)
        for target in targets:
            if factory in LOCK_FACTORIES:
                model.locks.add(target.id)
            elif factory in SYNCHRONIZED_FACTORIES:
                continue
            elif _is_mutable_init(value):
                model.global_candidates[target.id] = stmt.lineno

    # Names rebound via `global` in any function are shared module state
    # regardless of initializer shape (_fh = None, _warned = False, ...).
    rebound: dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    for name in sub.names:
                        rebound.setdefault(name, node.lineno)
    for name in rebound:
        if name in model.locks or name in model.global_candidates:
            continue
        # Defining line: the module-level assignment if there is one
        # (plain or annotated — `_x: str | None = None` counts too).
        lineno = None
        for stmt in tree.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            if any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
                lineno = stmt.lineno
                break
        # Anchor: the module-level assignment when there is one; else the
        # rebinding function's def line — purely lazy-init globals with no
        # module-level spelling are still shared state and must not
        # escape the catalog.
        model.global_candidates[name] = (
            lineno if lineno is not None else rebound[name])

    # Classes: locks + concurrency + attribute writers.
    for cls in [n for n in tree.body if isinstance(n, ast.ClassDef)]:
        locks: set[str] = set()
        is_thread = any("Thread" in (dotted_name(b) or "") for b in cls.bases)
        init = next((m for m in cls.body if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is not None:
            for stmt in ast.walk(init):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    tgts = (stmt.targets if isinstance(stmt, ast.Assign)
                            else [stmt.target])
                    value = stmt.value
                    for t in tgts:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            factory = _factory_of(value) if value else None
                            if factory in LOCK_FACTORIES:
                                locks.add(t.attr)
                            else:
                                # Every __init__-assigned attr gets a
                                # defining line — the anchor annotations
                                # and findings attach to.
                                model.attr_candidates.setdefault(
                                    (cls.name, t.attr), stmt.lineno)
        model.class_locks[cls.name] = locks
        if locks or is_thread:
            model.concurrent_classes.add(cls.name)
            for method in [m for m in cls.body
                           if isinstance(m, ast.FunctionDef)
                           and m.name != "__init__"]:
                for (attr, _node) in _self_attr_mutations(method):
                    model.attr_writers.setdefault(
                        (cls.name, attr), set()).add(method.name)
    return model


def _self_attr_mutations(fn: ast.FunctionDef):
    """(attr, node) for every mutation of ``self.<attr>`` in ``fn``:
    rebinds, augmented assigns, subscript stores/deletes, and mutator
    method calls."""

    def self_attr(node: ast.AST) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                attr = self_attr(t)
                if attr:
                    yield attr, node
                if isinstance(t, ast.Subscript):
                    attr = self_attr(t.value)
                    if attr:
                        yield attr, node
        elif isinstance(node, ast.AugAssign):
            attr = self_attr(node.target)
            if attr:
                yield attr, node
            if isinstance(node.target, ast.Subscript):
                attr = self_attr(node.target.value)
                if attr:
                    yield attr, node
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    attr = self_attr(t.value)
                    if attr:
                        yield attr, node
        elif isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in MUTATOR_METHODS):
                attr = self_attr(node.func.value)
                if attr:
                    yield attr, node


def _global_mutations(tree: ast.Module, name: str):
    """(node, fn) for every mutation of module-global ``name`` from inside
    a function: rebinds under a ``global`` declaration, subscript stores,
    aug-assigns, and mutator method calls."""
    for fn in [n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)]:
        declares_global = any(
            name in sub.names for sub in ast.walk(fn)
            if isinstance(sub, ast.Global))
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (isinstance(t, ast.Name) and t.id == name
                            and declares_global):
                        yield node, fn
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == name):
                        yield node, fn
            elif isinstance(node, ast.AugAssign):
                t = node.target
                if isinstance(t, ast.Name) and t.id == name and declares_global:
                    yield node, fn
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == name):
                    yield node, fn
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == name):
                        yield node, fn
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in MUTATOR_METHODS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == name):
                    yield node, fn


# --- lock-context resolution ---


def _lock_of_with_item(item: ast.withitem, model: ModuleModel,
                       cls: str | None) -> str | None:
    """Fully-qualified lock id for a with-item, or None if it is not a
    recognized lock: '<mod>.<name>' for module locks,
    '<mod>.<Class>.<attr>' for self locks, cross-module via import alias."""
    expr = item.context_expr
    if isinstance(expr, ast.Name):
        if expr.id in model.locks:
            return f"{model.modname}.{expr.id}"
        return None
    if isinstance(expr, ast.Attribute):
        if (isinstance(expr.value, ast.Name) and expr.value.id == "self"
                and cls is not None
                and expr.attr in model.class_locks.get(cls, ())):
            return f"{model.modname}.{cls}.{expr.attr}"
    return None


#: Scopes whose bodies run LATER, on whoever calls them — not under locks
#: held at the definition site (the Timer-callback false negative).
_DEFERRED_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _enclosing_locks(node: ast.AST, fn: ast.FunctionDef, model: ModuleModel,
                     cls: str | None) -> set[str]:
    """Locks actually held when ``node`` RUNS within ``fn``.  Walks real
    AST ancestry, not line spans: a with-item's context expression runs
    before acquisition, and a nested def/lambda body runs later on
    whatever thread invokes it — a lexical ``with lock:`` wrapped around
    a deferred body guards the *definition*, never the execution, so the
    ascent stops collecting at the first deferred-scope boundary (locks
    taken inside the nested body itself still count)."""
    parents: dict[ast.AST, ast.AST] = {}
    for sub in ast.walk(fn):
        for child in ast.iter_child_nodes(sub):
            parents[child] = sub
    held: set[str] = set()
    cur: ast.AST = node
    while cur is not fn:
        par = parents.get(cur)
        if par is None:
            break
        if isinstance(par, _DEFERRED_SCOPES) and par is not fn:
            break
        if isinstance(par, (ast.With, ast.AsyncWith)) and cur in par.body:
            for item in par.items:
                lock = _lock_of_with_item(item, model, cls)
                if lock:
                    held.add(lock)
        cur = par
    return held


def _short_lock(lock_id: str, model: ModuleModel, cls: str | None) -> str:
    """Render a lock id the way the annotation grammar wants it written at
    a use site in (model, cls): 'self._lock' or '_lock'."""
    parts = lock_id.split(".")
    if cls is not None and lock_id == f"{model.modname}.{cls}.{parts[-1]}":
        return f"self.{parts[-1]}"
    if lock_id == f"{model.modname}.{parts[-1]}":
        return parts[-1]
    return lock_id


def _resolve_annotation_lock(arg: str, model: ModuleModel,
                             cls: str | None) -> str | None:
    """The fully-qualified lock id an annotation argument names, or None
    (including the 'none: reason' escape, which returns the sentinel
    'none')."""
    arg = arg.strip()
    # The lock-free escape is exactly 'none: <reason>' — a prefix match
    # would let a typo'd lock name starting with 'none' silently disable
    # checking, and bare 'none' without a reason documents nothing.
    if arg.startswith("none:") and arg[5:].strip():
        return "none"
    name = arg[5:] if arg.startswith("self.") else arg
    if arg.startswith("self.") and cls is not None:
        if name in model.class_locks.get(cls, ()):
            return f"{model.modname}.{cls}.{name}"
        return None
    if name in model.locks:
        return f"{model.modname}.{name}"
    return None


# --- ICT007: guarded-by discipline ---


def check_guarded_by(models: list[ModuleModel]) -> list[Finding]:
    out: list[Finding] = []
    for model in models:
        sf = model.sf
        tree = sf.tree
        # Module globals.
        for name, lineno in sorted(model.global_candidates.items()):
            ann = sf.annotation(lineno, "guarded-by")
            mutations = list(_global_mutations(tree, name))
            if not mutations and ann is None:
                # A mutable literal nobody ever writes from a function
                # (__all__, a module-constant table) has nothing to guard.
                continue
            if ann is None:
                fix = _consistent_lock_fix(
                    mutations, model, None)
                out.append(sf.finding(
                    "ICT007/guarded-by", lineno,
                    f"module-global mutable state '{name}' (written from "
                    f"{len(mutations)} site(s)) has no "
                    f"'# ict: guarded-by(<lock>)' annotation",
                    fix_append=fix))
                continue
            lock = _resolve_annotation_lock(ann, model, None)
            if lock is None:
                out.append(sf.finding(
                    "ICT007/guarded-by", lineno,
                    f"'{name}' names unknown lock {ann!r} in its "
                    f"guarded-by annotation (declare the lock at module "
                    f"level or use 'none: <reason>')"))
                continue
            if lock == "none":
                continue
            for node, fn in mutations:
                held = _enclosing_locks(node, fn, model, None)
                if lock not in held:
                    out.append(sf.finding(
                        "ICT007/guarded-by", node.lineno,
                        f"write to '{name}' in {fn.name}() outside its "
                        f"declared lock "
                        f"'{_short_lock(lock, model, None)}'"))
        # Concurrent-class attributes.
        for cls in sorted(model.concurrent_classes):
            cls_node = next(n for n in tree.body
                            if isinstance(n, ast.ClassDef) and n.name == cls)
            methods = {m.name: m for m in cls_node.body
                       if isinstance(m, ast.FunctionDef)}
            for (owner, attr), writers in sorted(model.attr_writers.items()):
                if owner != cls:
                    continue
                mutations = [
                    (node, methods[m]) for m in sorted(writers)
                    for a, node in _self_attr_mutations(methods[m])
                    if a == attr]
                # Anchor: the __init__ assignment when there is one, else
                # the first mutation site (lazy-init attrs must not
                # escape the rule just because __init__ never names them).
                def_line = model.attr_candidates.get((cls, attr))
                anchor = def_line or min(n.lineno for n, _ in mutations)
                ann = sf.annotation(anchor, "guarded-by")
                if ann is None:
                    if len(writers) < 2:
                        continue  # single post-init writer: out of scope
                    fix = _consistent_lock_fix(mutations, model, cls)
                    where = ("its __init__ assignment" if def_line
                             else "its first write (no __init__ assignment)")
                    out.append(sf.finding(
                        "ICT007/guarded-by", anchor,
                        f"'{cls}.{attr}' is mutated from "
                        f"{len(writers)} methods "
                        f"({', '.join(sorted(writers))}) with no "
                        f"'# ict: guarded-by(<lock>)' annotation on "
                        f"{where}",
                        fix_append=fix))
                    continue
                lock = _resolve_annotation_lock(ann, model, cls)
                if lock is None:
                    out.append(sf.finding(
                        "ICT007/guarded-by", anchor,
                        f"'{cls}.{attr}' names unknown lock {ann!r} in "
                        f"its guarded-by annotation"))
                    continue
                if lock == "none":
                    continue
                for m in sorted(writers):
                    for a, node in _self_attr_mutations(methods[m]):
                        if a != attr:
                            continue
                        held = _enclosing_locks(node, methods[m], model, cls)
                        if lock not in held:
                            out.append(sf.finding(
                                "ICT007/guarded-by", node.lineno,
                                f"write to 'self.{attr}' in "
                                f"{cls}.{m}() outside its declared lock "
                                f"'{_short_lock(lock, model, cls)}'"))
    return out


def _consistent_lock_fix(mutations, model: ModuleModel,
                         cls: str | None) -> str | None:
    """When every mutation already runs under one common lock, the
    annotation is mechanical: --fix appends it."""
    if not mutations:
        return None
    commons: set[str] | None = None
    for node, fn in mutations:
        held = _enclosing_locks(node, fn, model, cls)
        commons = held if commons is None else (commons & held)
        if not commons:
            return None
    lock = sorted(commons)[0]
    return f"# ict: guarded-by({_short_lock(lock, model, cls)})"


# --- ICT008: lock-order inversions ---


def check_lock_order(models: list[ModuleModel]) -> list[Finding]:
    """Edges A->B when B is acquired while A is held — lexically, or via a
    call resolvable inside the analyzed set (same module, same class, or
    an imported analyzed module).  A cycle is reported once, at one of its
    acquisition sites."""
    # fn id: (modname, qualname) -> {"locks": set, "calls": set[fn id]}
    fn_map: dict[tuple[str, str], dict] = {}
    mod_by_name = {m.modname: m for m in models}
    alias_to_mod: dict[tuple[str, str], str] = {}
    for model in models:
        for alias, target in model.import_aliases.items():
            # "iterative_cleaner_tpu.obs.tracing" / "obs.tracing" endings.
            for other in models:
                if target.endswith(other.modname):
                    alias_to_mod[(model.modname, alias)] = other.modname

    def record_fn(model: ModuleModel, fn: ast.FunctionDef, cls: str | None):
        qual = f"{cls}.{fn.name}" if cls else fn.name
        info = {"locks": set(), "calls": set()}
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = _lock_of_with_item(item, model, cls)
                    if lock:
                        info["locks"].add(lock)
            elif isinstance(node, ast.Call):
                callee = _resolve_call(node, model, cls)
                if callee:
                    info["calls"].add(callee)
        fn_map[(model.modname, qual)] = info

    def _resolve_call(node: ast.Call, model: ModuleModel,
                      cls: str | None) -> tuple[str, str] | None:
        func = node.func
        if isinstance(func, ast.Name):
            return (model.modname, func.id)
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            base = func.value.id
            if base == "self" and cls is not None:
                return (model.modname, f"{cls}.{func.attr}")
            target_mod = alias_to_mod.get((model.modname, base))
            if target_mod:
                return (target_mod, func.attr)
        return None

    for model in models:
        tree = model.sf.tree
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                record_fn(model, node, None)
            elif isinstance(node, ast.ClassDef):
                for m in node.body:
                    if isinstance(m, ast.FunctionDef):
                        record_fn(model, m, node.name)

    # Transitive lock sets per function (may-acquire).
    acq_memo: dict[tuple[str, str], set[str]] = {}

    def may_acquire(fid: tuple[str, str], stack: frozenset) -> set[str]:
        if fid in acq_memo:
            return acq_memo[fid]
        if fid not in fn_map or fid in stack:
            return set()
        info = fn_map[fid]
        locks = set(info["locks"])
        for callee in info["calls"]:
            locks |= may_acquire(callee, stack | {fid})
        if not stack:
            # Memoize ROOT results only: a result computed mid-recursion
            # may be truncated by the cycle guard above (a recursive call
            # back into the stack contributes set()), and caching that
            # partial set would permanently hide lock edges through the
            # cycle — the detector's whole purpose.
            acq_memo[fid] = locks
        return locks

    # Edges with one example site each.
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, model: ModuleModel, lineno: int, why: str):
        if a != b:
            edges.setdefault((a, b), (model.sf.path, lineno, why))

    for model in models:
        tree = model.sf.tree
        scopes: list[tuple[ast.FunctionDef, str | None]] = []
        for node in tree.body:
            if isinstance(node, ast.FunctionDef):
                scopes.append((node, None))
            elif isinstance(node, ast.ClassDef):
                scopes.extend((m, node.name) for m in node.body
                              if isinstance(m, ast.FunctionDef))
        for fn, cls in scopes:
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    held = [
                        lock for item in node.items
                        for lock in [_lock_of_with_item(item, model, cls)]
                        if lock]
                    if not held:
                        continue
                    for sub in ast.walk(node):
                        if sub is node:
                            continue
                        if isinstance(sub, (ast.With, ast.AsyncWith)):
                            for item in sub.items:
                                inner = _lock_of_with_item(item, model, cls)
                                if inner:
                                    for a in held:
                                        add_edge(a, inner, model, sub.lineno,
                                                 "nested with")
                        elif isinstance(sub, ast.Call):
                            callee = _resolve_call(sub, model, cls)
                            if callee:
                                for b in may_acquire(callee, frozenset()):
                                    for a in held:
                                        add_edge(
                                            a, b, model, sub.lineno,
                                            f"call to "
                                            f"{callee[0]}.{callee[1]}()")

    # Cycle detection over the edge graph.
    graph: dict[str, set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    out: list[Finding] = []
    reported: set[frozenset] = set()

    def dfs(start: str, node: str, path: list[str], seen: set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) >= 1:
                cyc = frozenset(path + [start])
                if cyc in reported:
                    continue
                reported.add(cyc)
                cycle = path + [start, start]
                edge = edges[(path[-1], start)] if (path[-1], start) in edges \
                    else edges[(start, path[0])]
                src, lineno, why = edge
                sf = next(m.sf for m in models if m.sf.path == src)
                out.append(sf.finding(
                    "ICT008/lock-order", lineno,
                    "lock-order inversion: "
                    + " -> ".join(path + [start, path[0]])
                    + f" (edge here: {why}); threads taking these locks "
                    "in different orders can deadlock"))
            elif nxt not in seen:
                dfs(start, nxt, path + [nxt], seen | {nxt})

    for node in sorted(graph):
        dfs(node, node, [node], {node})
    return out


def run_race_rules(files: list[SourceFile]) -> list[Finding]:
    in_scope = [sf for sf in files
                if sf.path.startswith(RACE_SCOPE_PREFIXES)
                and not sf.parse_error]
    models = [build_model(sf) for sf in in_scope]
    return check_guarded_by(models) + check_lock_order(models)
