"""ICT004/bench-exit: bench.py prints its one JSON line on EVERY exit path.

The contract (CLAUDE.md, pinned at runtime by tests/test_bench_payload.py
and content-checked by tools/perf_gate.py's payload-contract blocks — this
rule is the *static* half of that pair): every way the bench process can
terminate must be dominated by a call to ``_emit`` (the one function that
prints the payload line and mirrors it into docs/).

The check is a small dominance walk over bench.py's statement-level CFG.
Python blocks are linear statement lists, so "X dominates exit E" reduces
to: walking outward from E through its enclosing blocks (stopping at the
owning function boundary — an emit in an *enclosing def* happened at a
different time, not on this path), some statement strictly before E's
position **always emits**.  A statement always-emits when it is an
``_emit(...)`` call, an ``if`` whose branches BOTH always-emit, a ``with``
whose body does, or a ``try`` whose body and every handler do.  This is
conservative: a path that emits only conditionally does not count.

Checked exits: every ``return`` in ``main``, and every ``os._exit`` /
``sys.exit`` / ``raise SystemExit`` anywhere in the file — except the
module-level ``sys.exit(main())`` trampoline, whose payload emission is
``main``'s own obligation (already checked).
"""

from __future__ import annotations

import ast

from iterative_cleaner_tpu.analysis.engine import Finding, SourceFile
from iterative_cleaner_tpu.analysis.rules import dotted_name

EMIT_FN = "_emit"
#: The function whose returns are process exits (rc for sys.exit).
MAIN_FN = "main"


def _is_emit_stmt(stmt: ast.stmt) -> bool:
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        name = dotted_name(stmt.value.func) or ""
        return name.split(".")[-1] == EMIT_FN
    return False


def _always_emits(stmt: ast.stmt) -> bool:
    if _is_emit_stmt(stmt):
        return True
    if isinstance(stmt, ast.If):
        return (bool(stmt.orelse)
                and _block_emits(stmt.body) and _block_emits(stmt.orelse))
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return _block_emits(stmt.body)
    if isinstance(stmt, ast.Try):
        return (_block_emits(stmt.body)
                and all(_block_emits(h.body) for h in stmt.handlers))
    return False


def _block_emits(stmts: list[ast.stmt]) -> bool:
    return any(_always_emits(s) for s in stmts)


def _exit_dominated(path: list[tuple[list[ast.stmt], int]]) -> bool:
    """``path`` is the chain of (enclosing statement list, index of the
    statement on the way to the exit) from the owning function's body down
    to the exit statement itself."""
    for stmts, idx in reversed(path):
        if _block_emits(stmts[:idx]):
            return True
    return False


def _walk_exits(fn_body: list[ast.stmt]):
    """Yield (exit_node, kind, chain) for every exit statement under this
    function body, NOT descending into nested function defs (their exits
    are their own paths — walked separately)."""

    def visit(stmts: list[ast.stmt], chain):
        for idx, stmt in enumerate(stmts):
            here = chain + [(stmts, idx)]
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs run on their own paths
            if isinstance(stmt, ast.Return):
                yield stmt, "return", here
                continue
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                name = None
                if isinstance(stmt.exc, ast.Call):
                    name = dotted_name(stmt.exc.func)
                elif isinstance(stmt.exc, ast.Name):
                    name = stmt.exc.id
                if name == "SystemExit":
                    yield stmt, "raise SystemExit", here
            sub_blocks = [getattr(stmt, f, None)
                          for f in ("body", "orelse", "finalbody")]
            handlers = getattr(stmt, "handlers", None)
            cases = getattr(stmt, "cases", None)   # match statements
            if any(sub_blocks) or handlers or cases:
                for sub in sub_blocks:
                    if sub:
                        yield from visit(sub, here)
                for handler in handlers or ():
                    yield from visit(handler.body, here)
                for case in cases or ():
                    yield from visit(case.body, here)
            else:
                for call in _exit_calls(stmt):
                    yield call, dotted_name(call.func), here

    yield from visit(fn_body, [])


def _exit_calls(stmt: ast.stmt):
    """os._exit / sys.exit calls inside a simple (non-compound) statement."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name in ("os._exit", "sys.exit", "exit", "quit"):
                yield node


def rule_bench_exit(sf: SourceFile) -> list[Finding]:
    # Exactly the repo-root bench.py: that file alone carries the one-line
    # JSON payload contract (a future tools/microbench.py owes nothing).
    if sf.path != "bench.py" or sf.tree is None:
        return []
    out: list[Finding] = []

    fns = {n.name: n for n in ast.walk(sf.tree)
           if isinstance(n, ast.FunctionDef)}
    if EMIT_FN not in fns or MAIN_FN not in fns:
        out.append(sf.finding(
            "ICT004/bench-exit", 1,
            f"bench.py must define '{EMIT_FN}' (the one-line JSON print) "
            f"and '{MAIN_FN}' — the exit-path contract has no anchor "
            f"without them"))
        return out

    # Every function body is walked for hard exits (os._exit can hide in a
    # watchdog thread); 'return' exits are an obligation of main only.
    for fn in fns.values():
        if fn.name in (EMIT_FN,):
            continue  # the emitter itself is the dominator, not a client
        for node, kind, chain in _walk_exits(fn.body):
            if kind == "return" and fn.name != MAIN_FN:
                continue
            if _exit_dominated(chain):
                continue
            out.append(sf.finding(
                "ICT004/bench-exit", node.lineno,
                f"exit path ({kind} in '{fn.name}') is not dominated by "
                f"an {EMIT_FN}() call: bench.py must print its one-line "
                f"JSON payload on EVERY exit path (CLAUDE.md; runtime "
                f"half: tools/perf_gate.py payload-contract checks)"))

    # Module-level exits: only the sys.exit(main()) trampoline is allowed.
    for stmt in sf.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # function-level exits were walked above
        for node in _exit_calls(stmt):
            args = node.args
            if (dotted_name(node.func) == "sys.exit" and len(args) == 1
                    and isinstance(args[0], ast.Call)
                    and dotted_name(args[0].func) == MAIN_FN):
                continue
            out.append(sf.finding(
                "ICT004/bench-exit", node.lineno,
                "module-level hard exit bypasses main()'s emit-dominated "
                "paths; only 'sys.exit(main())' is allowed"))
    return out
