"""Compiled-artifact contracts: jaxpr/HLO checks on the four clean routes.

Source lint catches what a human wrote; this layer checks what XLA is
actually going to run.  Each registered route — stepwise, fused, chunked,
sharded — is traced on a tiny cube (abstract avals: no device buffers, no
real compile beyond lowering) and three contracts are asserted:

- **no host callbacks** — a ``pure_callback``/``io_callback``/debug
  primitive inside a route would punch a host round-trip into the hot
  loop (and deadlock under the daemon's one-device-owner threading
  model); the jaxpr must not contain one, at any nesting depth.
- **dtype lattice** — the oracle's numpy.ma pipeline promotes 3 of the 4
  diagnostics to f64 *on the host*; the jax route's side of the parity
  contract is that it stays uniformly 32-bit (SURVEY §8.L9) — any f64 /
  complex128 aval in a traced route means someone mixed the two worlds
  and the masks will drift.  The trace runs with ``jax_enable_x64``
  temporarily ON: with it off, jax silently demotes every 64-bit request
  at trace time and the check could never fire.  Only *strong* 64-bit
  avals are forbidden — under x64 every Python scalar literal passes
  through as a weak f64 that immediately converts back to f32, which is
  exactly the demotion behavior the f32 route relies on, while a real
  ``astype(float64)`` / ``np.float64`` constant is strong and is caught.
  (``--x64`` routes are the operator's explicit opt-in and not traced.)
- **donation ledger** — buffer-donation annotations silently vanish when
  a wrapper re-jits or an alias is dropped at lowering; the lowered
  StableHLO's donation markers must match :data:`ROUTE_DONATIONS`
  exactly.  The ingest PR registered the first intentional donations
  (stepwise: 1, chunked: 3 — see the ledger's own comment for what each
  buffer is and why it is safe); changing a donation means updating the
  ledger in the same PR — that is the contract doing its job.

Run via ``tools/ict_lint.py --contracts`` (CI: ``JAX_PLATFORMS=cpu``).
Imports jax lazily so the source/race layers stay import-light; callers
must pin the platform *before* this module traces (the CLI does — the
CLAUDE.md wedged-tunnel quirk).
"""

from __future__ import annotations

from iterative_cleaner_tpu.analysis.engine import Finding

#: Tiny trace shape: nbin >= 3 (the parity floor), everything else minimal
#: but structurally representative (nsub/nchan big enough for the robust
#: scalers' medians to be nondegenerate).
TINY_SHAPE = (4, 8, 64)
TINY_BATCH = 2
TINY_MAX_ITER = 3

#: route -> expected donation-marker count in the lowered module.  A PR
#: that adds jax donation (e.g. donate_argnums on an ingest path) must
#: bump its route here — the checker fails on any mismatch, in BOTH
#: directions (a vanished donation is a silent perf regression; an
#: unexpected one is a correctness hazard for callers that reuse inputs).
#:
#: Registered donations (the ingest PR; all internal-only buffers — D, w0,
#: valid and every other caller-owned input stay undonated):
#:
#: - stepwise: 1 — ``advance_template`` donates its carried template
#:   (T_prev aliases the equally-shaped output; the carry is dead the
#:   moment its successor exists).
#: - chunked: 3 — ``_sparse_template_update`` donates the carried template
#:   (1), and ``_finish`` donates the freshly-concatenated d_std / d_mean
#:   maps, which alias the (test, new_w) outputs (2).
#: - fused / sharded: 0 by design — their array inputs are caller-owned
#:   and reused across calls (bench re-dispatches on the same device cube),
#:   so donation there would be a correctness hazard, not an optimisation.
ROUTE_DONATIONS = {
    "stepwise": 1,
    "fused": 0,
    "chunked": 3,
    "sharded": 0,
}

#: Substrings of primitive names that mean "host round-trip".
CALLBACK_MARKERS = ("callback", "outside_call", "infeed", "outfeed")

#: 64-bit avals forbidden on the f32 parity routes.
FORBIDDEN_DTYPES = ("float64", "complex128")

#: StableHLO attribute names jax uses to mark donated/aliased inputs.
DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


def _finding(route: str, label: str, kind: str, message: str) -> Finding:
    # ``kind`` (callback / dtype / donation / ...) goes into the snippet —
    # the fingerprint basis — so baselining one violation class for a
    # route can never suppress a *different* future violation at the same
    # route/label.
    return Finding(rule="ICT009/route-contract",
                   path="iterative_cleaner_tpu/analysis/contracts.py",
                   line=1, snippet=f"{route}:{label}:{kind}",
                   message=f"[{route}/{label}] {message}")


def _walk_jaxpr(jaxpr, seen: set) -> list:
    """Every eqn of a (closed) jaxpr, recursing through sub-jaxprs in eqn
    params (pjit / while / cond / scan bodies)."""
    core = getattr(jaxpr, "jaxpr", jaxpr)
    if id(core) in seen:
        return []
    seen.add(id(core))
    eqns = []
    for eqn in core.eqns:
        eqns.append(eqn)
        for val in eqn.params.values():
            for sub in _iter_jaxprs(val):
                eqns.extend(_walk_jaxpr(sub, seen))
    return eqns


def _iter_jaxprs(val):
    # Type-name matching, not isinstance: the public home of Jaxpr /
    # ClosedJaxpr has moved across jax versions (jax.core -> jax.extend)
    # and this must not chase it.
    if type(val).__name__ in ("Jaxpr", "ClosedJaxpr"):
        yield val
    elif isinstance(val, (list, tuple)):
        for item in val:
            yield from _iter_jaxprs(item)


def _check_jaxpr(route: str, label: str, closed) -> list[Finding]:
    out: list[Finding] = []
    eqns = _walk_jaxpr(closed, set())
    for eqn in eqns:
        prim = eqn.primitive.name
        if any(marker in prim for marker in CALLBACK_MARKERS):
            out.append(_finding(
                route, label, "callback",
                f"host-callback primitive '{prim}' in the traced route: "
                f"the hot loop must stay device-only"))
    bad: set[str] = set()
    for eqn in eqns:
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if (dtype is not None and str(dtype) in FORBIDDEN_DTYPES
                    # Weak 64-bit scalars are Python literals mid-demotion
                    # (module docstring); only strong avals are real leaks.
                    and not getattr(aval, "weak_type", False)):
                bad.add(f"{prim_name(eqn)}:{dtype}")
    if bad:
        out.append(_finding(
            route, label, "dtype",
            f"64-bit avals in the f32 parity route ({sorted(bad)[:4]}): "
            f"the jax side of the oracle's f64-promotion split must stay "
            f"uniformly 32-bit (SURVEY §8.L9)"))
    return out


def prim_name(eqn) -> str:
    return getattr(eqn.primitive, "name", "?")


def _count_donations(lowered) -> int:
    text = lowered.as_text()
    return sum(text.count(marker) for marker in DONATION_MARKERS)


def _route_lowerings():
    """(route, label, lowered, closed_jaxpr) for every registered route's
    jit entry points, traced on the tiny shape.  Every entry point a route
    dispatches is covered — the chunked route is four small kernels, not
    one."""
    import jax
    import jax.numpy as jnp  # noqa: F401 — backend init before tracing
    import numpy as np

    from iterative_cleaner_tpu.backends.jax_backend import (
        advance_template,
        clean_step,
        fused_clean,
        step_from_template,
    )
    from iterative_cleaner_tpu.parallel.chunked import (
        _block_stats,
        _block_stats_pallas,
        _finish,
        _partial_template,
        _sparse_template_update,
    )
    from iterative_cleaner_tpu.parallel.sharded import batched_fused_clean

    nsub, nchan, nbin = TINY_SHAPE
    f32, b1 = np.float32, np.bool_
    D = jax.ShapeDtypeStruct((nsub, nchan, nbin), f32)
    w = jax.ShapeDtypeStruct((nsub, nchan), f32)
    v = jax.ShapeDtypeStruct((nsub, nchan), b1)
    t = jax.ShapeDtypeStruct((nbin,), f32)
    s = jax.ShapeDtypeStruct((), f32)
    pr = (0.0, 0.0, 1.0)

    from iterative_cleaner_tpu.backends.jax_backend import (
        INCREMENTAL_TEMPLATE_BUDGET,
    )

    budget = min(INCREMENTAL_TEMPLATE_BUDGET, nsub * nchan)
    dvals = jax.ShapeDtypeStruct((budget,), f32)
    profs = jax.ShapeDtypeStruct((budget, nbin), f32)
    Db = jax.ShapeDtypeStruct((TINY_BATCH, nsub, nchan, nbin), f32)
    wb = jax.ShapeDtypeStruct((TINY_BATCH, nsub, nchan), f32)
    vb = jax.ShapeDtypeStruct((TINY_BATCH, nsub, nchan), b1)
    nstat = jax.ShapeDtypeStruct((nsub, nchan), f32)

    entries = [
        # The stepwise route: dense step, incremental step + the sparse
        # template advance it carries between iterations — each in both
        # lowerings (XLA, and the Pallas stats megakernel that is the
        # r06 TPU default; off-TPU the trace captures the interpret-mode
        # pallas_call, whose inner jaxpr the same checks walk).
        ("stepwise", "clean_step", clean_step,
         (D, w, v, w, s, s), {"pulse_region": pr, "use_pallas": False}),
        ("stepwise", "clean_step_pallas", clean_step,
         (D, w, v, w, s, s), {"pulse_region": pr, "use_pallas": True}),
        ("stepwise", "step_from_template", step_from_template,
         (D, w, v, t, s, s), {"pulse_region": pr, "use_pallas": False}),
        ("stepwise", "advance_template", advance_template,
         (D, t, w, w), {}),
        # The fused route (the CLI/daemon default: incremental template).
        ("fused", "fused_clean", fused_clean, (D, w, v, s, s),
         {"max_iter": TINY_MAX_ITER, "pulse_region": pr,
          "want_residual": False, "use_pallas": False, "incremental": True}),
        ("fused", "fused_clean_pallas", fused_clean, (D, w, v, s, s),
         {"max_iter": TINY_MAX_ITER, "pulse_region": pr,
          "want_residual": False, "use_pallas": True, "incremental": True}),
        # The chunked (>HBM streaming) route's five kernels.
        ("chunked", "partial_template", _partial_template, (D, w), {}),
        ("chunked", "block_stats", _block_stats, (D, t, w, v),
         {"pulse_region": pr, "want_resid": False}),
        ("chunked", "block_stats_pallas", _block_stats_pallas, (D, t, w, v),
         {"pulse_region": pr, "interpret": True}),
        ("chunked", "sparse_template_update", _sparse_template_update,
         (t, dvals, profs), {}),
        ("chunked", "finish", _finish,
         (nstat, nstat, nstat, nstat, v, w, s, s), {}),
        # The sharded batch route (vmapped fused loop; shardings are
        # call-time input properties, the traced computation is this).
        # The pallas variant pins the vmapped megakernel lowering the
        # non-mesh batch path may take; mesh-sharded dispatches keep it
        # off by policy (see batched_fused_clean's docstring).
        ("sharded", "batched_fused_clean", batched_fused_clean,
         (Db, wb, vb, s, s),
         {"max_iter": TINY_MAX_ITER, "pulse_region": pr}),
        ("sharded", "batched_fused_clean_pallas", batched_fused_clean,
         (Db, wb, vb, s, s),
         {"max_iter": TINY_MAX_ITER, "pulse_region": pr,
          "use_pallas": True}),
    ]
    for route, label, fn, args, kwargs in entries:
        lowered = fn.lower(*args, **kwargs)
        # The jaxpr view for primitive/dtype checks: trace the same jit
        # callable (make_jaxpr sees through pjit into the full program).
        closed = jax.make_jaxpr(
            lambda *a, _fn=fn, _kw=kwargs: _fn(*a, **_kw))(*args)
        yield route, label, lowered, closed


def check_routes() -> list[Finding]:
    """All contracts on all routes; an un-traceable route is itself a
    finding (the checker must never silently skip a route)."""
    import jax

    findings: list[Finding] = []
    seen_routes: set[str] = set()
    # x64 ON for the trace (restored after): with it off, jax demotes
    # every 64-bit request at trace time and the dtype contract would be
    # vacuously green — see the module docstring.
    x64_before = bool(jax.config.jax_enable_x64)
    try:
        jax.config.update("jax_enable_x64", True)
        lowerings = list(_route_lowerings())
    except Exception as exc:  # noqa: BLE001 — surfaced as a finding
        return [_finding("all", "trace", "trace-failure",
                         f"route tracing failed: {type(exc).__name__}: "
                         f"{exc}")]
    finally:
        jax.config.update("jax_enable_x64", x64_before)
    donations: dict[str, int] = {}
    for route, label, lowered, closed in lowerings:
        seen_routes.add(route)
        findings.extend(_check_jaxpr(route, label, closed))
        donations[route] = donations.get(route, 0) + _count_donations(lowered)
    for route, expected in sorted(ROUTE_DONATIONS.items()):
        if route not in seen_routes:
            findings.append(_finding(
                route, "coverage", "untraced",
                "route registered in ROUTE_DONATIONS but not traced — "
                "add its entry points to _route_lowerings()"))
            continue
        got = donations.get(route, 0)
        if got != expected:
            findings.append(_finding(
                route, "donation", "count-drift",
                f"donation markers in lowered HLO: expected {expected}, "
                f"found {got} — donation annotations "
                f"{'vanished at lowering' if got < expected else 'appeared unregistered'}; "
                f"update ROUTE_DONATIONS only with the intentional change"))
    for route in seen_routes - set(ROUTE_DONATIONS):
        findings.append(_finding(
            route, "coverage", "unregistered",
            "traced route has no ROUTE_DONATIONS entry — register its "
            "expected donation count"))
    return findings


def pin_cpu_for_contracts() -> None:
    """The CLI front door for offline runs: pin the CPU backend before the
    first trace (env var AND config update — the CLAUDE.md recipe; a
    wedged dev tunnel hangs first backend init process-wide otherwise).
    Honors an explicit operator override via ICT_TEST_TPU=1."""
    import os

    if os.environ.get("ICT_TEST_TPU"):
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — the env var still protects subprocs
        pass
