"""Cleaning configuration.

Field-for-field superset of the reference CLI (reference
``iterative_cleaner.py:15-41``): every flag of the original argparse interface
is represented, plus the TPU-framework extensions (``backend``, ``fused``,
``dtype``).

Note on ``pulse_region``: the reference's help text claims the order is
``(pulse_start, pulse_end, scaling_factor)`` but the code reads
``[scale, start, end]`` (reference ``iterative_cleaner.py:279-282``; SURVEY.md
§8.L5).  We replicate the *code* semantics and document the true order.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass


def pulse_region_active(pulse_region) -> bool:
    """The reference's disable gate: ``pulse_region != [0, 0, 1]``
    (iterative_cleaner.py:279).  Shared by config and both backends so the
    sentinel can never drift."""
    return tuple(float(v) for v in pulse_region) != (0.0, 0.0, 1.0)


def pulse_region_bin_scale(nbin: int, pulse_region, dtype="float32"):
    """Static per-bin residual scale implementing the reference's
    ``err2[int(start):int(end)] *= scale`` with its true argument order
    [scale, start, end] (§8.L5).  Built with a real Python slice so negative
    / out-of-range indices behave exactly like the reference; shared by the
    XLA and Pallas paths so their semantics can never drift."""
    import numpy as np

    scale, start, end = pulse_region
    bin_scale = np.ones(nbin, dtype=dtype)
    bin_scale[int(start):int(end)] = scale
    return bin_scale


def warn_zero_threshold(stacklevel: int = 2) -> None:
    """Shared by CleanConfig validation and the --sweep grid check: the
    reference accepts thresh=0 (every |scaled|/0 becomes inf/NaN and
    essentially everything unmasked is zapped), so we do too — but 0/0 ties
    break differently between numpy.ma's mixed f32/f64 pipeline and the
    device's uniform dtype, so the bit-identical-mask guarantee does not
    cover it."""
    import warnings

    warnings.warn(
        "a threshold of exactly 0 divides every scaled diagnostic by zero; "
        "results are degenerate and mask parity vs the numpy oracle is not "
        "guaranteed", stacklevel=stacklevel + 1)


@dataclass(frozen=True)
class CleanConfig:
    # --- algorithm parameters (reference flags) ---
    chanthresh: float = 5.0        # -c: sigma threshold along a channel
    subintthresh: float = 5.0      # -s: sigma threshold along a subint
    max_iter: int = 5              # -m: maximum number of iterations (must be >= 1)
    # (scale, start_bin, end_bin); (0, 0, 1) disables. Bins are in the
    # dedispersed phase frame (reference iterative_cleaner.py:99-100).
    pulse_region: tuple[float, float, float] = (0.0, 0.0, 1.0)  # -r
    bad_chan: float = 1.0          # --bad_chan: zap channel if zapped-subint frac > this
    bad_subint: float = 1.0        # --bad_subint: zap subint if zapped-chan frac > this

    # --- output / driver policy (reference flags) ---
    output: str = ""               # -o: '' = <orig>_cleaned, 'std' = NAME.FREQ.MJD
    pscrunch: bool = False         # -p: pscrunch the *output* archive
    memory: bool = False           # --memory: keep full-pol archive in memory
    unload_res: bool = False       # -u: write the residual archive
    print_zap: bool = False        # -z: write the zap plot
    quiet: bool = False            # -q
    no_log: bool = False           # -l

    # --- TPU framework extensions ---
    backend: str = "numpy"         # {'numpy', 'jax'}
    fused: bool = False            # jax: run the whole loop as one lax.while_loop
    pallas: bool | None = None     # jax: fused Pallas stats megakernel.
                                   # None (default) = AUTO: on whenever it is
                                   # a real optimisation (TPU + viable shape +
                                   # no residual/x64 request — see
                                   # ops/pallas_kernels.resolve_use_pallas);
                                   # True forces it (errors on impossible
                                   # combos below), False forces XLA.
    x64: bool = False              # jax: use float64 intermediates for bit parity
    sharded_batch: bool = False    # clean same-shape archives together on the mesh
    auto_shard: bool = True        # shard one cube over devices when it exceeds HBM
    chunk_block: int = 0           # force the single-device streaming backend
                                   # with this subint block size (0 = automatic)
    incremental_template: bool = True  # jax stepwise/fused/chunked: carry
                                   # the template across iterations,
                                   # updating it from flipped profiles
                                   # (saves a cube pass/iteration; residual
                                   # requests force the dense route)
    stream: bool = False           # sharded_batch: dispatch buckets as loads complete
    resume: bool = False           # skip archives whose cleaned output exists
    dump_masks: bool = False       # save mask history NPZ next to the output
    audit: bool = False            # shadow-oracle parity audit: after each
                                   # clean, replay the inputs through the
                                   # numpy oracle and compare masks
                                   # bit-for-bit (obs/audit.py; a mismatch
                                   # writes a repro bundle); no-op on the
                                   # numpy backend (it IS the oracle)
    trace_dir: str = ""            # jax.profiler trace output directory (the
                                   # one-shot CLI capture; the serving
                                   # daemon's bounded on-demand captures
                                   # live in obs/profiling.py)

    def __post_init__(self) -> None:
        if self.max_iter < 1:
            # The reference crashes with an unbound-variable NameError when
            # max_iter == 0 (reference iterative_cleaner.py:152; SURVEY.md
            # §8.L10). We reject it up front instead.
            raise ValueError(f"max_iter must be >= 1, got {self.max_iter}")
        if self.chanthresh == 0 or self.subintthresh == 0:
            warn_zero_threshold(stacklevel=3)  # through the generated __init__
        if self.backend not in ("numpy", "jax"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.fused and self.backend != "jax":
            raise ValueError("fused=True requires backend='jax'")
        if self.pallas and self.backend != "jax":
            raise ValueError("pallas=True requires backend='jax'")
        if self.pallas and self.unload_res:
            # The Pallas kernel never materialises the residual cube (that is
            # its point); the residual archive needs the XLA route.
            raise ValueError("pallas=True cannot produce the residual "
                             "archive; drop --unload_res or --pallas")
        if self.pallas and self.x64:
            # Mosaic has no f64, and x64's bit-parity promise is about
            # matching numpy's reduction order, which the kernel's tiled
            # reductions cannot guarantee anyway.
            raise ValueError("pallas=True does not support x64=True "
                             "(no float64 on the TPU Pallas path)")
        if self.pallas and self.sharded_batch:
            # vmap-under-GSPMD of pallas_call is not wired up; rejecting
            # beats silently running the batch on the XLA route while
            # clean.log records pallas=True.
            raise ValueError("pallas=True is not supported with "
                             "sharded_batch=True yet; drop one of them")
        if self.sharded_batch and self.backend != "jax":
            raise ValueError("sharded_batch=True requires backend='jax'")
        if self.chunk_block < 0:
            raise ValueError(f"chunk_block must be >= 0, got {self.chunk_block}")
        if self.chunk_block and self.backend != "jax":
            raise ValueError("chunk_block requires backend='jax'")
        if self.chunk_block and self.sharded_batch:
            # The sharded-batch driver never routes through the single-cube
            # chunked backend; rejecting beats silently ignoring the flag.
            raise ValueError("chunk_block is not supported with "
                             "sharded_batch=True; drop one of them")
        if self.stream and not self.sharded_batch:
            raise ValueError("stream=True only applies to sharded_batch=True")
        if len(self.pulse_region) != 3:
            raise ValueError("pulse_region must have exactly 3 elements")
        object.__setattr__(self, "pulse_region", tuple(float(v) for v in self.pulse_region))

    @property
    def pulse_region_active(self) -> bool:
        return pulse_region_active(self.pulse_region)

    def replace(self, **kw) -> "CleanConfig":
        return dataclasses.replace(self, **kw)

    def namespace_repr(self, archives: list[str]) -> str:
        """An argparse.Namespace-style repr, for clean.log parity with the
        reference log format (reference iterative_cleaner.py:173-176)."""
        fields = [
            ("archive", archives),
            ("chanthresh", self.chanthresh),
            ("subintthresh", self.subintthresh),
            ("max_iter", self.max_iter),
            ("print_zap", self.print_zap),
            ("unload_res", self.unload_res),
            ("pscrunch", self.pscrunch),
            ("quiet", self.quiet),
            ("no_log", self.no_log),
            ("pulse_region", list(self.pulse_region)),
            ("output", self.output),
            ("memory", self.memory),
            ("bad_chan", self.bad_chan),
            ("bad_subint", self.bad_subint),
            ("backend", self.backend),
            ("fused", self.fused),
            ("pallas", self.pallas),
            ("x64", self.x64),
            ("sharded_batch", self.sharded_batch),
            ("chunk_block", self.chunk_block),
            ("incremental_template", self.incremental_template),
        ]
        inner = ", ".join(f"{k}={v!r}" for k, v in fields)
        return f"Namespace({inner})"
