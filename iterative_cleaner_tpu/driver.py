"""Per-archive driver: load → clean → side outputs → save.

The host orchestration layer above the model (reference ``main()`` +
``clean()``'s output plumbing, iterative_cleaner.py:44-61, 147-177): output
naming modes, the residual archive, the zap plot, and the append-only
clean.log audit trail.  One corrupt archive must not kill a batch
(SURVEY.md §5 "failure detection"), so per-archive errors are isolated.
"""

from __future__ import annotations

import datetime
import os
import sys
from dataclasses import dataclass

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.base import Archive, get_io, known_extension as _ext
from iterative_cleaner_tpu.models.surgical import SurgicalCleaner, SurgicalOutput


def output_name(cfg: CleanConfig, archive: Archive, path: str) -> str:
    """Reference naming modes (iterative_cleaner.py:47-57):

    - default: ``<original name>_cleaned<ext>`` (the reference appends to the
      *full* original filename, extension included);
    - ``-o std``: ``NAME.FREQ.MJD<ext>`` with FREQ %.3f and mid-MJD %f;
    - ``-o <name>``: used verbatim.
    """
    if cfg.output == "":
        return f"{path}_cleaned{_ext(path)}"
    if cfg.output == "std":
        return "%s.%.3f.%f%s" % (
            archive.source,
            archive.centre_frequency,
            archive.mjd_mid,
            _ext(path),
        )
    return cfg.output


def residual_name(path: str, loops: int) -> str:
    # Reference: "%s_residual_%s.ar" % (ar_name, loops)  (:161)
    return f"{path}_residual_{loops}{_ext(path)}"


@dataclass
class ArchiveReport:
    path: str
    out_path: str | None
    loops: int = 0
    rfi_frac: float = 0.0
    converged: bool = False
    error: str | None = None


def process_archive(
    path: str,
    cfg: CleanConfig,
    log_dir: str = ".",
    all_paths: list[str] | None = None,
) -> ArchiveReport:
    """Clean one archive.  ``all_paths`` is the full batch invocation (the
    reference logs the entire args Namespace, archive list included, in every
    log line — iterative_cleaner.py:173-176)."""
    io = get_io(path)
    archive = io.load(path)

    def progress(info):
        if not cfg.quiet:
            print(f"Loop: {info.index}")
            print(
                "Differences to previous weights: %s  RFI fraction: %s"
                % (info.diff_weights, info.rfi_frac)
            )

    if not cfg.quiet:
        print("Total number of profiles: %s" % archive.weights.size)
    cleaner = SurgicalCleaner(cfg)
    out: SurgicalOutput = cleaner.clean(archive, progress=progress)
    res = out.result

    if not cfg.quiet:
        if res.converged:
            print("RFI removal stops after %s loops." % res.loops)
        else:
            print(
                "Cleaning was interrupted after the maximum amount of loops (%s)"
                % cfg.max_iter
            )
        if out.n_bad_subints + out.n_bad_channels != 0:
            print(
                "Removed %s bad subintegrations and %s bad channels."
                % (out.n_bad_subints, out.n_bad_channels)
            )

    o_name = output_name(cfg, archive, path)
    io.save(out.cleaned, o_name)

    if cfg.unload_res and out.residual is not None:
        io.save(out.residual, residual_name(path, res.loops))

    if cfg.print_zap:
        from iterative_cleaner_tpu.utils.plotting import save_zap_plot

        save_zap_plot(res.test_results, path, cfg.chanthresh, cfg.subintthresh)

    if not cfg.no_log:
        # Reference log line format (:173-176).
        with open(os.path.join(log_dir, "clean.log"), "a") as fh:
            fh.write(
                "\n %s: Cleaned %s with %s, required loops=%s"
                % (
                    datetime.datetime.now(),
                    path,
                    cfg.namespace_repr(all_paths if all_paths is not None else [path]),
                    res.loops,
                )
            )

    if not cfg.quiet:
        print("Cleaned archive: %s" % o_name)
    return ArchiveReport(
        path=path,
        out_path=o_name,
        loops=res.loops,
        rfi_frac=res.rfi_frac,
        converged=res.converged,
    )


def run(paths: list[str], cfg: CleanConfig, log_dir: str = ".") -> list[ArchiveReport]:
    """Sequential batch with per-archive failure isolation.  (The sharded
    multi-device batch lives in :mod:`.parallel.batch`.)"""
    reports = []
    for path in paths:
        try:
            reports.append(
                process_archive(path, cfg, log_dir=log_dir, all_paths=paths))
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            reports.append(ArchiveReport(path=path, out_path=None, error=str(exc)))
            # Failures are never silenced — -q only gates progress chatter.
            print(f"ERROR cleaning {path}: {exc}", file=sys.stderr)
    return reports
