"""Per-archive driver: load → clean → side outputs → save.

The host orchestration layer above the model (reference ``main()`` +
``clean()``'s output plumbing, iterative_cleaner.py:44-61, 147-177): output
naming modes, the residual archive, the zap plot, and the append-only
clean.log audit trail.  One corrupt archive must not kill a batch
(SURVEY.md §5 "failure detection"), so per-archive errors are isolated in
both the sequential and the sharded-batch paths.
"""

from __future__ import annotations

import datetime
import os
import sys
from dataclasses import dataclass, field

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.base import Archive, get_io, known_extension as _ext
from iterative_cleaner_tpu.models.surgical import SurgicalCleaner, SurgicalOutput


def output_name(cfg: CleanConfig, archive: Archive | None, path: str) -> str:
    """Reference naming modes (iterative_cleaner.py:47-57):

    - default: ``<original name>_cleaned<ext>`` (the reference appends to the
      *full* original filename, extension included);
    - ``-o std``: ``NAME.FREQ.MJD<ext>`` with FREQ %.3f and mid-MJD %f;
    - ``-o <name>``: used verbatim.
    """
    if cfg.output == "":
        return f"{path}_cleaned{_ext(path)}"
    if cfg.output == "std":
        return "%s.%.3f.%f%s" % (
            archive.source,
            archive.centre_frequency,
            archive.mjd_mid,
            _ext(path),
        )
    return cfg.output


def residual_name(path: str, loops: int) -> str:
    # Reference: "%s_residual_%s.ar" % (ar_name, loops)  (:161)
    return f"{path}_residual_{loops}{_ext(path)}"


@dataclass
class ArchiveReport:
    path: str
    out_path: str | None
    loops: int = 0
    rfi_frac: float = 0.0
    converged: bool = False
    error: str | None = None
    skipped: bool = False          # --resume: output already existed
    # Host wall-clock per iteration (stepwise paths; --fused is one device
    # dispatch and the sharded batch one per bucket, so both leave this
    # empty rather than reporting zeros).
    iteration_s: list[float] = field(default_factory=list)
    # --audit: the shadow-oracle parity record (obs/audit.py), carried into
    # the --report JSON; a divergence includes the repro-bundle path.
    audit: dict | None = None


def split_resumable(paths: list[str], cfg: CleanConfig):
    """--resume support (SURVEY.md §5 checkpoint/resume gap): a batch that
    died partway is rerun with --resume and only the archives whose cleaned
    output is not already on disk are processed.

    Returns (todo_paths, skipped) with ``skipped`` keyed by the archive's
    index in the *original* list, so the caller can hand back reports in
    invocation order.  Only the default naming mode has a path-derivable
    output name; 'std' and explicit -o names cannot be checked without
    loading the archive, so --resume leaves those to run (and says so once).
    """
    if not cfg.resume:
        return paths, {}
    if cfg.output != "":
        print("warning: --resume only skips archives in the default naming "
              "mode (-o was given); cleaning everything", file=sys.stderr)
        return paths, {}
    todo, skipped = [], {}
    for k, path in enumerate(paths):
        # archive is only consulted by the 'std' mode, excluded above
        o_name = output_name(cfg, None, path)
        if os.path.exists(o_name):
            skipped[k] = ArchiveReport(path=path, out_path=o_name, skipped=True)
            if not cfg.quiet:
                print(f"Resume: {o_name} exists, skipping {path}")
        else:
            todo.append(path)
    return todo, skipped


def _merge_reports(
    n: int, skipped: dict[int, ArchiveReport], done: list[ArchiveReport]
) -> list[ArchiveReport]:
    """Reports in invocation order: skipped ones back at their original
    indices, processed ones filling the gaps in sequence."""
    it = iter(done)
    return [skipped[k] if k in skipped else next(it) for k in range(n)]


def atomic_save(io, archive: Archive, o_name: str) -> None:
    """Write-then-rename so a crash mid-save never leaves a truncated file
    under the final name — --resume trusts bare existence of the output, so
    a partial file from a killed run would otherwise be kept as the final
    product.  Every IO backend writes to the exact path it is given (NpzIO
    goes through a file object for this), so the temp suffix is arbitrary."""
    tmp = f"{o_name}.part"
    io.save(archive, tmp)
    os.replace(tmp, o_name)


def dump_masks(
    o_name: str, history, test_results, loops: int, converged: bool
) -> None:
    """Mask audit dump (SURVEY.md §5 checkpoint gap) alongside the cleaned
    archive.  ``history`` (per-iteration masks, pre-loop weights first) is
    tracked by the stepwise and fused paths; the sharded batch does not
    carry it and omits the key rather than writing an empty lie — consumers
    check ``"history" in npz``."""
    import numpy as np

    payload = dict(test_results=test_results, loops=loops, converged=converged)
    if history:
        payload["history"] = np.stack(history)
    np.savez_compressed(f"{o_name}_masks.npz", **payload)


def emit_outputs(
    io,
    archive: Archive,
    path: str,
    cleaned: Archive,
    test_results,
    loops: int,
    converged: bool,
    rfi_frac: float,
    cfg: CleanConfig,
    log_dir: str,
    all_paths: list[str],
    history=None,
    iteration_s: list[float] | None = None,
) -> ArchiveReport:
    """The side-output block shared by the sequential and sharded-batch
    drivers: save, zap plot, mask dump, clean.log line, report."""
    o_name = output_name(cfg, archive, path)
    atomic_save(io, cleaned, o_name)

    if cfg.print_zap:
        from iterative_cleaner_tpu.utils.plotting import save_zap_plot

        save_zap_plot(test_results, path, cfg.chanthresh, cfg.subintthresh)

    if cfg.dump_masks:
        dump_masks(o_name, history, test_results, loops, converged)

    if not cfg.no_log:
        # Reference log line format (:173-176).
        with open(os.path.join(log_dir, "clean.log"), "a") as fh:
            fh.write(
                "\n %s: Cleaned %s with %s, required loops=%s"
                % (
                    datetime.datetime.now(),
                    path,
                    cfg.namespace_repr(all_paths),
                    loops,
                )
            )

    if not cfg.quiet:
        print("Cleaned archive: %s" % o_name)
    return ArchiveReport(
        path=path,
        out_path=o_name,
        loops=loops,
        rfi_frac=rfi_frac,
        converged=converged,
        iteration_s=iteration_s or [],
    )


def process_archive(
    path: str,
    cfg: CleanConfig,
    log_dir: str = ".",
    all_paths: list[str] | None = None,
    archive: Archive | None = None,
) -> ArchiveReport:
    """Clean one archive.  ``all_paths`` is the full batch invocation (the
    reference logs the entire args Namespace, archive list included, in every
    log line — iterative_cleaner.py:173-176).  ``archive`` skips the load
    (the prefetching batch loop decodes ahead of the device)."""
    io = get_io(path)
    if archive is None:
        archive = io.load(path)

    def progress(info):
        if not cfg.quiet:
            print(f"Loop: {info.index}")
            print(
                "Differences to previous weights: %s  RFI fraction: %s"
                % (info.diff_weights, info.rfi_frac)
            )

    if not cfg.quiet:
        print("Total number of profiles: %s" % archive.weights.size)
    from iterative_cleaner_tpu.obs import events
    from iterative_cleaner_tpu.obs.tracing import profile_trace

    if events.active():
        # CLI entry point of the replay contract (proving/traces.py):
        # job_submitted must carry tenant / shape bucket / config salt /
        # arrival ts wherever work enters, so an event log recorded from
        # a batch CLI run replays the same as one from the daemon.  The
        # bucket grammar is the scheduler's NSUBxNCHANxNBIN (data is
        # (nsub, npol, nchan, nbin) — pol is not a bucketing axis).
        from iterative_cleaner_tpu.ingest import cas as _cas
        from iterative_cleaner_tpu.service.scheduler import bucket_label
        s = archive.data.shape
        shape_hint = [int(s[0]), int(s[2]), int(s[3])]
        events.emit("job_submitted", path=path, entry="cli",
                    replica_id="", job_id="", tenant="", idem_key="",
                    cache_salt=_cas.cache_salt(cfg), shape=shape_hint,
                    bucket=bucket_label(shape_hint))

    cleaner = SurgicalCleaner(cfg)
    with profile_trace(cfg.trace_dir), \
            events.span("clean_archive", path=path,
                        shape=list(archive.data.shape)):
        out: SurgicalOutput = cleaner.clean(archive, progress=progress)
    res = out.result

    if not cfg.quiet:
        if res.converged:
            print("RFI removal stops after %s loops." % res.loops)
        else:
            print(
                "Cleaning was interrupted after the maximum amount of loops (%s)"
                % cfg.max_iter
            )
        if out.n_bad_subints + out.n_bad_channels != 0:
            print(
                "Removed %s bad subintegrations and %s bad channels."
                % (out.n_bad_subints, out.n_bad_channels)
            )

    if cfg.unload_res and out.residual is not None:
        io.save(out.residual, residual_name(path, res.loops))

    report = emit_outputs(
        io,
        archive,
        path,
        out.cleaned,
        res.test_results,
        res.loops,
        res.converged,
        res.rfi_frac,
        cfg,
        log_dir,
        all_paths if all_paths is not None else [path],
        history=res.history,
        # The fused single-dispatch loop has no per-iteration host laps;
        # its result says so (timed=False) — report nothing for it rather
        # than a list of zeros.
        iteration_s=[i.duration_s for i in res.iterations] if res.timed
        else None,
    )
    if out.audit is not None:
        report.audit = out.audit
        if not out.audit.get("mask_identical", True):
            # A parity break is never silenced (-q gates chatter only):
            # the output was still written, but the operator must know the
            # jax route disagreed with the executable spec.
            print(f"AUDIT DIVERGENCE {path}: "
                  f"{out.audit.get('n_mask_diffs')} mask bit(s) differ "
                  f"from the numpy oracle"
                  + (f"; repro bundle at {out.audit['bundle']}"
                     if out.audit.get("bundle") else ""),
                  file=sys.stderr)
        elif not cfg.quiet and "skipped" not in out.audit:
            print("Audit: mask identical to the numpy oracle "
                  f"(max score drift "
                  f"{out.audit.get('max_score_drift', 0) or 0:.2e})")
    return report


# Fraction of host RAM the all-at-once batch loader may plausibly fill
# before the driver flips to the streaming dispatcher by itself (VERDICT
# r05 item 5).  The estimate is the batch's on-disk size — compressed NPZ
# underestimates the decoded cubes, so the fraction is conservative.
STREAM_RAM_FRACTION = 0.25


def _host_ram_bytes() -> int:
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        return 0


def _stream_threshold_bytes() -> int:
    """On-disk batch size above which --sharded_batch streams by default;
    0 disables the auto-flip.  ICT_STREAM_THRESHOLD_BYTES overrides (tests
    and hosts where sysconf lies)."""
    env = os.environ.get("ICT_STREAM_THRESHOLD_BYTES")
    if env is not None:
        try:
            return int(float(env))
        except ValueError:
            print(f"warning: ignoring unparseable ICT_STREAM_THRESHOLD_BYTES"
                  f"={env!r} (want a byte count); using the host-RAM default",
                  file=sys.stderr)
    return int(_host_ram_bytes() * STREAM_RAM_FRACTION)


def _auto_stream(paths: list[str], cfg: CleanConfig) -> bool:
    """Whether this batch should take the streaming route even without
    --stream: the all-at-once loader holds every decoded cube on host
    during bucketing, which an above-RAM-threshold directory cannot
    afford (masks are identical either way; only emission order and host
    residency differ)."""
    if cfg.stream:
        return True
    threshold = _stream_threshold_bytes()
    if threshold <= 0:
        return False
    total = 0
    for p in paths:
        try:
            total += os.path.getsize(p)
        except OSError:
            continue  # missing files fail per-archive later, as always
    if total > threshold:
        if not cfg.quiet:
            print(
                f"note: batch on-disk size ({total / 1e9:.1f} GB) exceeds "
                f"the host-memory threshold ({threshold / 1e9:.1f} GB); "
                "using the streaming dispatcher (bounded host residency — "
                "pass --stream to silence this note)", file=sys.stderr)
        return True
    return False


def run_sharded_batch(
    paths: list[str],
    cfg: CleanConfig,
    log_dir: str = ".",
    mesh=None,
    all_paths: list[str] | None = None,
) -> list[ArchiveReport]:
    """Multi-archive cleaning on the device mesh (one dispatch per same-shape
    bucket).  Residual archives are not produced in this mode (the fused
    kernel does not carry them); use the sequential driver for --unload_res.

    In --stream mode outputs are emitted (and each item's host arrays
    released) as its bucket finishes, so host residency stays bounded by the
    read-ahead window; the all-at-once mode emits after the whole batch."""
    from iterative_cleaner_tpu.models.surgical import apply_output_policy
    from iterative_cleaner_tpu.parallel.batch import (
        clean_directory_batch,
        clean_directory_streaming,
    )
    from iterative_cleaner_tpu.utils.tracing import profile_trace

    if cfg.unload_res:
        print(
            "warning: --unload_res is not supported with --sharded_batch; "
            "residuals will not be written", file=sys.stderr)
    if cfg.dump_masks:
        # Every other mode conflict is rejected loudly at config time; this
        # one only degrades the NPZ payload, so it warns instead (VERDICT
        # round-2 weak #8).
        print(
            "warning: --sharded_batch tracks no per-iteration mask history; "
            "--dump_masks will write the NPZ without the 'history' key",
            file=sys.stderr)
    invocation = all_paths if all_paths is not None else paths
    reports: dict[int, ArchiveReport] = {}

    def emit_item(i, item) -> None:
        if item.error is None:
            try:
                cleaned = apply_output_policy(item.archive, item.weights, cfg)
                reports[i] = emit_outputs(
                    get_io(item.path),
                    item.archive,
                    item.path,
                    cleaned,
                    item.test_results,
                    item.loops,
                    item.converged,
                    item.rfi_frac,
                    cfg,
                    log_dir,
                    invocation,
                )
                # Release the decoded archive + masks: this is what makes
                # --stream's host-memory bound real.
                item.archive = item.weights = item.test_results = None
                return
            except Exception as exc:  # noqa: BLE001 — isolate, report, continue
                item.error = str(exc)
        print(f"ERROR cleaning {item.path}: {item.error}", file=sys.stderr)
        reports[i] = ArchiveReport(
            path=item.path, out_path=None, error=item.error)

    with profile_trace(cfg.trace_dir):
        if _auto_stream(paths, cfg):
            items = clean_directory_streaming(
                paths, cfg, mesh=mesh, on_item=emit_item)
        else:
            items = clean_directory_batch(paths, cfg, mesh=mesh)
    for i, item in enumerate(items):
        if i not in reports:  # all-at-once mode, and failed loads in stream
            emit_item(i, item)
    return [reports[i] for i in range(len(items))]


def run_follow(
    paths: list[str],
    cfg: CleanConfig,
    poll_s: float = 1.0,
    idle_timeout_s: float = 30.0,
    alert_iters: int = 2,
    log_dir: str = ".",
    sleep=None,
) -> list[ArchiveReport]:
    """--follow: tail each growing archive through the online subsystem
    (online/follow.py), sequentially, with the sequential driver's
    per-archive failure isolation — a dead stream must not kill the
    observation's sibling follows.  ``sleep`` is the tail loop's injectable
    wait (tests drive growth deterministically through it)."""
    from iterative_cleaner_tpu.online.follow import follow_archive

    invocation = list(paths)
    reports = []
    for path in paths:
        try:
            reports.append(follow_archive(
                path, cfg, poll_s=poll_s, idle_timeout_s=idle_timeout_s,
                alert_iters=alert_iters, log_dir=log_dir,
                all_paths=invocation, sleep=sleep))
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            reports.append(ArchiveReport(path=path, out_path=None,
                                         error=str(exc)))
            print(f"ERROR following {path}: {exc}", file=sys.stderr)
    return reports


def write_report(
    reports: list[ArchiveReport], path: str, cfg: CleanConfig | None = None
) -> None:
    """Machine-readable batch summary (--report): one JSON object per
    archive, written atomically.  The reference's only machine-readable
    artifact is the free-text clean.log (iterative_cleaner.py:173-176);
    pipelines that schedule thousands of archives need a parseable verdict.

    In a multi-host run each process holds only its slice of the batch, so
    the path gets a per-process suffix — otherwise the hosts would all
    os.replace the same file and the last writer's slice would masquerade
    as the whole batch."""
    import dataclasses
    import json

    if cfg is not None and cfg.backend == "jax":
        from iterative_cleaner_tpu.parallel.multihost import process_topology

        pi, pc = process_topology()
        if pc > 1:
            path = f"{path}.p{pi}"
    payload = [dataclasses.asdict(r) for r in reports]
    tmp = f"{path}.part"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, path)


def run_sweep(
    paths: list[str], cfg: CleanConfig, pairs: list[tuple[float, float]]
) -> list[ArchiveReport]:
    """--sweep mode: per archive, run the whole threshold grid as one
    batched device dispatch (models/sweep.py), print the table, save
    ``<path>_sweep.npz``.  Exploratory — no cleaned archives, no clean.log."""
    from iterative_cleaner_tpu.config import warn_zero_threshold
    from iterative_cleaner_tpu.models.sweep import (
        format_table,
        save_sweep,
        sweep_thresholds,
    )
    from iterative_cleaner_tpu.ops.preprocess import preprocess

    if any(c == 0 or s == 0 for c, s in pairs):
        # Sweep thresholds are traced scalars that never pass through a
        # CleanConfig, so the degenerate-threshold check fires here.
        warn_zero_threshold()

    if cfg.backend != "jax":
        print("error: --sweep requires --backend=jax", file=sys.stderr)
        return [ArchiveReport(path=p, out_path=None,
                              error="--sweep requires backend='jax'")
                for p in paths]
    # Same multi-host split as run(): without it every process would sweep
    # every archive and race on the same _sweep.npz outputs.
    from iterative_cleaner_tpu.parallel.multihost import partition_paths

    paths = partition_paths(paths)
    reports = []
    for path in paths:
        try:
            archive = get_io(path).load(path)
            D, w0 = preprocess(archive)
            points = sweep_thresholds(D, w0, cfg, pairs)
            print(f"Sweep {path} ({len(points)} threshold pairs):")
            print(format_table(points))
            out = f"{path}_sweep.npz"
            save_sweep(points, out)
            reports.append(ArchiveReport(path=path, out_path=out))
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            reports.append(ArchiveReport(path=path, out_path=None, error=str(exc)))
            print(f"ERROR sweeping {path}: {exc}", file=sys.stderr)
    return reports


def run(paths: list[str], cfg: CleanConfig, log_dir: str = ".") -> list[ArchiveReport]:
    """Sequential batch with per-archive failure isolation and one-archive
    read-ahead: while the device cleans archive k, a loader thread decodes
    archive k+1 (SURVEY.md §2.4 "async" row — the reference is strictly
    serial).  (The sharded multi-device batch lives in
    :mod:`.parallel.batch`.)"""
    from concurrent.futures import ThreadPoolExecutor

    # clean.log records the full invocation (reference :173-176) even when
    # resume/multi-host trims what this process actually cleans.
    invocation = list(paths)
    if cfg.backend == "jax":
        # Multi-host: each process cleans its round-robin slice of the batch
        # (identity in single-process runs).  The numpy path stays JAX-free:
        # process_index() would initialize the device runtime.
        from iterative_cleaner_tpu.parallel.multihost import partition_paths

        paths = partition_paths(paths)
    n_total = len(paths)
    paths, skipped = split_resumable(paths, cfg)
    if cfg.sharded_batch:
        return _merge_reports(
            n_total, skipped,
            run_sharded_batch(paths, cfg, log_dir=log_dir, all_paths=invocation))

    def load(path: str):
        try:
            return get_io(path).load(path), None
        except Exception as exc:  # noqa: BLE001 — isolate, report, continue
            return None, str(exc)

    reports = []
    with ThreadPoolExecutor(max_workers=1) as pool:
        fut = pool.submit(load, paths[0]) if paths else None
        for k, path in enumerate(paths):
            archive, err = fut.result()
            fut = pool.submit(load, paths[k + 1]) if k + 1 < len(paths) else None
            if err is None:
                try:
                    reports.append(process_archive(
                        path, cfg, log_dir=log_dir, all_paths=invocation,
                        archive=archive))
                    continue
                except Exception as exc:  # noqa: BLE001
                    err = str(exc)
            reports.append(ArchiveReport(path=path, out_path=None, error=err))
            # Failures are never silenced — -q only gates progress chatter.
            print(f"ERROR cleaning {path}: {err}", file=sys.stderr)
    return _merge_reports(n_total, skipped, reports)
