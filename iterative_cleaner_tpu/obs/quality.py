"""RFI data-quality telemetry: what the cleaner *decided*, as metrics.

The serving daemon's existing telemetry says how fast jobs move and what
they cost; nothing says what the science got — a drifting receiver or an
RFI storm shows up as "the daemon is healthy, the data is ruined".  This
module turns every finished clean's mask into a handful of cheap,
aggregatable facts (all O(nsub·nchan) host ops on a mask already in
hand):

- the **zap fraction** (per job, plus a cumulative distribution across
  jobs);
- **per-channel / per-subint occupancy**: for each channel, the fraction
  of its subints zapped (and vice versa), histogrammed over fixed
  fraction buckets — a single hot channel and a uniform storm produce the
  same zap fraction but opposite occupancy histograms;
- **per-diagnostic attribution rates** (when ``ICT_FORENSICS=1`` filled
  the per-iteration ``zaps_by_diagnostic`` records — :mod:`.forensics`):
  which of std / mean / ptp / fft is doing the zapping;
- the **termination-reason mix** (fixed_point / cycle / max_iter): a
  rising max_iter rate means masks stopped converging.

Everything lands in the :mod:`.tracing` registries (rendered on the
daemon's ``/metrics`` under ``ict_rfi_*`` / ``ict_jobs_terminated_total``)
and in the JSON :func:`quality_summary` dict the daemon attaches to job
manifests and :class:`..core.cleaner.CleanResult` exposes.  Strictly
read-only on the math: summaries are computed from finished masks and
never feed back.
"""

from __future__ import annotations

import numpy as np

from iterative_cleaner_tpu.obs import tracing

#: Fixed occupancy/zap-fraction bucket upper bounds (fractions, cumulative
#: ``le`` semantics; the implicit last bucket is 1.0 = fully zapped).
#: Fixed, not adaptive, for the same reason as tracing.HIST_BOUNDS: every
#: job shares one layout, so cross-job aggregation is addition.
FRACTION_BOUNDS: tuple[float, ...] = (
    0.0, 0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def fraction_hist(fractions: np.ndarray) -> list[int]:
    """Cumulative counts of ``fractions`` (values in [0, 1]) at each
    :data:`FRACTION_BOUNDS` bound — ``hist[-1] == len(fractions)`` by
    construction (every fraction is <= 1.0)."""
    f = np.asarray(fractions, dtype=np.float64).ravel()
    return [int(np.sum(f <= bound)) for bound in FRACTION_BOUNDS]


def quality_summary(weights, termination: str = "") -> dict:
    """One mask's data-quality facts as a JSON-ready dict.

    ``weights`` is a final (nsub, nchan) weights array — zapped entries are
    exactly 0.0 on every route (the invariant rfi_frac already rests on).
    """
    w = np.asarray(weights)
    zap = w == 0
    nsub, nchan = zap.shape
    chan_occ = zap.mean(axis=0)     # per-channel zapped-subint fraction
    sub_occ = zap.mean(axis=1)      # per-subint zapped-channel fraction
    out = {
        "zap_frac": float(zap.mean()),
        "n_zapped": int(zap.sum()),
        "n_profiles": int(zap.size),
        "channels_fully_zapped": int(np.sum(chan_occ == 1.0)),
        "subints_fully_zapped": int(np.sum(sub_occ == 1.0)),
        "channel_occupancy_max": float(chan_occ.max()) if nchan else 0.0,
        "subint_occupancy_max": float(sub_occ.max()) if nsub else 0.0,
        # Cumulative counts at FRACTION_BOUNDS (see fraction_hist).
        "occupancy_bounds": list(FRACTION_BOUNDS),
        "channel_occupancy_hist": fraction_hist(chan_occ),
        "subint_occupancy_hist": fraction_hist(sub_occ),
    }
    if termination:
        out["termination"] = termination
    return out


def record_job_quality(summary: dict, timeline=None) -> None:
    """Account one finished job's :func:`quality_summary` into the metrics
    registries (the /metrics view an alert can watch).  ``timeline`` is the
    job's per-iteration forensics records, mined for per-diagnostic
    attribution when ``ICT_FORENSICS`` filled them.  Never raises —
    telemetry must not fail the job it describes."""
    try:
        frac = float(summary.get("zap_frac", 0.0))
        # Mean zap fraction across jobs = sum / count; the last-job gauge
        # is the "what did the most recent clean look like" spot check.
        tracing.count("rfi_zap_fraction_sum", frac)
        tracing.count("rfi_zap_fraction_count")
        tracing.set_gauge("rfi_last_job_zap_frac", frac)
        for bound in FRACTION_BOUNDS:
            if frac <= bound:
                tracing.count_labeled("rfi_job_zap_fraction_total",
                                      {"le": repr(float(bound))})
        # Occupancy histograms aggregate per CHANNEL / SUBINT, summed over
        # jobs (each job contributes its cumulative bucket counts).
        bounds = summary.get("occupancy_bounds", FRACTION_BOUNDS)
        for axis in ("channel", "subint"):
            hist = summary.get(f"{axis}_occupancy_hist")
            if not hist:
                continue
            for bound, n in zip(bounds, hist):
                if n:
                    tracing.count_labeled(
                        f"rfi_{axis}_occupancy_total",
                        {"le": repr(float(bound))}, n)
        reason = summary.get("termination")
        if reason:
            tracing.count_labeled("jobs_terminated_total", {"reason": reason})
        for rec in timeline or ():
            votes = (rec.get("zaps_by_diagnostic")
                     if isinstance(rec, dict) else None)
            for name, n in (votes or {}).items():
                if n:
                    tracing.count_labeled("rfi_zaps_attributed_total",
                                          {"diagnostic": str(name)}, n)
    except Exception:  # noqa: BLE001 — quality accounting is best-effort
        pass
