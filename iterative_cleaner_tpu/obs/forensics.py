"""Convergence forensics: *why* a clean converged and *which* diagnostic
zapped what.

The core loop always records the cheap facts — per-iteration mask churn
(XOR popcount vs the previous iteration = ``IterationInfo.diff_weights``),
newly-zapped / restored profile counts, and the termination reason (fixed
point / cycle / max_iter) on :class:`..core.cleaner.CleanResult`.  This
module adds the expensive one: per-diagnostic zap attribution, an optional
host-side replay of the numpy oracle's score pipeline for one iteration
that counts, per diagnostic (std / mean / ptp / fft), how many of the
profiles zapped that iteration the diagnostic itself voted for (its own
scaled value >= 1; the combined score is the median of the four, so a zap
carries at least two votes).

Strictly read-only on the math: attribution recomputes scores from the
same frozen inputs the backends use and never touches a mask.  It is also
deliberately expensive (a full numpy stats pass per iteration), so it is
gated behind ``ICT_FORENSICS=1`` rather than riding along with every
telemetry sink — event logs stay cheap, deep attribution is asked for.
"""

from __future__ import annotations

import os

import numpy as np

#: Diagnostic order matches the oracle's ``comprehensive_stats`` list.
DIAGNOSTIC_NAMES = ("std", "mean", "ptp", "fft")


def attribution_enabled() -> bool:
    return os.environ.get("ICT_FORENSICS") == "1"


def timeline_enabled() -> bool:
    """Whether the serving daemon should pay for per-job iteration
    timelines on the batched route (a mask-history fetch per bucket): on
    with an active telemetry sink or ICT_FORENSICS=1.  The oracle route
    records its timeline unconditionally — its iterations are already on
    host for free."""
    from iterative_cleaner_tpu.obs import events

    return events.enabled() or attribution_enabled()


def attribute_zaps(D: np.ndarray, w0: np.ndarray, w_prev: np.ndarray,
                   new_w: np.ndarray, cfg) -> dict[str, int]:
    """Per-diagnostic vote counts among the profiles zapped this iteration.

    ``w_prev`` is the template weighting the iteration ran with; ``new_w``
    its output mask.  Reuses the oracle's own building blocks
    (backends/numpy_backend) so the attribution can never drift from the
    spec it explains."""
    from iterative_cleaner_tpu.backends.numpy_backend import (
        build_template,
        fit_template,
        scaled_diagnostics,
    )

    D = np.asarray(D, np.float32)
    w0 = np.asarray(w0, np.float32)
    template = build_template(D, np.asarray(w_prev, np.float32))
    _amp, resid = fit_template(D, template, cfg.pulse_region)
    weighted = resid * w0[..., None]
    mask3d = np.repeat(np.expand_dims(~w0.astype(bool), 2),
                       D.shape[-1], axis=2)
    data_ma = np.ma.masked_array(weighted, mask=mask3d)
    zapped = (np.asarray(new_w) == 0) & (w0 != 0)
    out: dict[str, int] = {}
    for name, score in zip(DIAGNOSTIC_NAMES,
                           scaled_diagnostics(data_ma, cfg)):
        with np.errstate(invalid="ignore"):
            out[name] = int(np.sum(zapped & (np.asarray(score) >= 1)))
    return out


def attribute_from_backend(backend, w_prev, new_w) -> dict[str, int] | None:
    """Attribution via whatever host inputs the backend exposes (the
    oracle's ``D``/``w0``, the chunked backend's ``_D``/``_w0``); None when
    a backend keeps no host-reachable cube — attribution is best-effort."""
    D = getattr(backend, "D", None)
    if D is None:
        D = getattr(backend, "_D", None)
    w0 = getattr(backend, "w0", None)
    if w0 is None:
        w0 = getattr(backend, "_w0", None)
    cfg = getattr(backend, "cfg", None)
    if D is None or w0 is None or cfg is None:
        return None
    try:
        return attribute_zaps(np.asarray(D), np.asarray(w0),
                              np.asarray(w_prev), np.asarray(new_w), cfg)
    except Exception:  # noqa: BLE001 — forensics must never fail the clean
        return None


def termination_reason(converged: bool, history) -> str:
    """Post-hoc termination classification from a mask history (the fused
    kernel's ring-buffer prefix): the loop stopped because the final mask
    reproduced the immediately previous one (``fixed_point``), an older one
    (``cycle``), or never reproduced any (``max_iter``)."""
    if not converged:
        return "max_iter"
    if len(history) >= 2 and np.array_equal(history[-1], history[-2]):
        return "fixed_point"
    return "cycle"


def iteration_record(info) -> dict:
    """One IterationInfo as the JSON-ready timeline entry the daemon's
    ``GET /jobs/<id>/trace`` serves and the event log carries."""
    rec = {
        "index": info.index,
        "diff_weights": info.diff_weights,
        "n_new_zaps": info.n_new_zaps,
        "n_unzapped": info.n_unzapped,
        "rfi_frac": info.rfi_frac,
        "duration_s": round(info.duration_s, 6),
    }
    if info.zaps_by_diagnostic is not None:
        rec["zaps_by_diagnostic"] = dict(info.zaps_by_diagnostic)
    return rec
