"""Memory + compiled-executable cost accounting.

One module owns every ``device.memory_stats()`` read in the tree (the
ad-hoc probe that lived in ``parallel/autoshard.py`` delegates here, so
the autoshard routing decision and the exported gauges can never disagree
about what a device reported), plus host RSS and XLA's own static
accounting (``cost_analysis()`` / ``memory_analysis()``) of the compiled
executables, keyed by the same shape-bucket labels
``note_compiled_shape`` tracks.

Everything lands in :mod:`.tracing` gauges — rendered on the daemon's
``/metrics`` (``ict_hbm_bytes_in_use{device=...}``,
``ict_route_hbm_peak_bytes{route=...}``, ``ict_host_rss_bytes``,
``ict_executable_*{shape_bucket=...}``) — and in the JSON
:func:`memory_report` that bench.py attaches to its one-line payload on
every exit path and the daemon attaches to job manifests.

Strictly read-only on the math, and strictly *optional* on the platform:
CPU backends report no memory stats, a numpy-mode daemon never imports
jax, and nothing here may trigger a backend init (a wedged tunnel would
turn a metrics scrape into a process-wide hang — the CLAUDE.md quirk), so
every device read first checks that a backend is already live.
"""

from __future__ import annotations

import os
import shutil

from iterative_cleaner_tpu.obs import tracing

_ENV_OVERRIDE = "ICT_HBM_BYTES"

#: Devices whose memory_stats() raised once (backends without
#: introspection raise the same way forever — don't pay the exception per
#: scrape).  Lock-free on purpose: set.add of a value that is a static
#: fact of the device is idempotent under any interleaving.
_stats_unsupported: set = set()  # ict: guarded-by(none: idempotent value-stable cache)

#: shape_bucket -> executable analysis dict (analyze once per bucket; the
#: AOT compile behind it is the expensive part and the answer is static).
#: Lock-free on purpose: every writer stores the same static analysis for
#: a key, so the worst race costs one duplicate AOT compile, never a
#: wrong value.
_exec_registry: dict[str, dict] = {}  # ict: guarded-by(none: idempotent value-stable cache)


def hbm_override_bytes() -> int | None:
    """The ``ICT_HBM_BYTES`` escape hatch (tests, and hosts where the
    runtime misreports) — honored before any device is touched."""
    env = os.environ.get(_ENV_OVERRIDE)
    if env:
        return int(env)
    return None


def backend_live() -> bool:
    """Whether a JAX backend is already initialized in this process.  The
    gate every device read here sits behind: observability must never be
    the thing that triggers (and possibly hangs) first backend init."""
    from iterative_cleaner_tpu.utils.device_probe import _backend_liveness

    return _backend_liveness() == "live"


def device_stats(device) -> dict | None:
    """One device's ``memory_stats()``, or None when the backend has no
    introspection (remembered per device kind so the exception is paid
    once, not per scrape)."""
    key = (getattr(device, "platform", ""), getattr(device, "id", -1))
    if key in _stats_unsupported:
        return None
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backend without memory introspection
        _stats_unsupported.add(key)
        return None
    return stats if stats else None


def device_memory_bytes(device=None, default_device_fn=None) -> int | None:
    """Best-effort per-device memory capacity (autoshard's routing input).

    Resolution order: the ``ICT_HBM_BYTES`` env override, the device's
    ``memory_stats()['bytes_limit']`` (TPU), else None (unknown — e.g. CPU
    backends report no limit).  ``default_device_fn`` supplies the device
    lazily so the env-override path never touches a backend."""
    env = hbm_override_bytes()
    if env is not None:
        return env
    if device is None:
        if default_device_fn is not None:
            device = default_device_fn()
        else:
            if not backend_live():
                return None
            import jax

            device = jax.devices()[0]  # ict: backend-init-ok(gated on backend_live() above)
    stats = device_stats(device)
    if stats is None:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None


def host_rss_bytes() -> int:
    """This process's resident set, from /proc (Linux) with a
    getrusage fallback; 0 when neither works."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        # ru_maxrss is kilobytes on Linux (peak, not current — the honest
        # fallback is still better than 0).
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # noqa: BLE001 — accounting is best-effort
        return 0


def device_snapshot() -> list[dict]:
    """Per-local-device memory view (empty when no backend is live or the
    platform has no introspection)."""
    if not backend_live():
        return []
    try:
        import jax

        devices = jax.local_devices()  # ict: backend-init-ok(gated on backend_live() above)
    except Exception:  # noqa: BLE001 — introspection is best-effort
        return []
    out = []
    for dev in devices:
        stats = device_stats(dev)
        if stats is None:
            continue
        out.append({
            "device": f"{dev.platform}:{dev.id}",
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(stats.get("bytes_limit", 0)),
        })
    return out


def update_process_gauges() -> None:
    """Refresh the current/peak HBM gauges per device and the host RSS
    gauge — the daemon's tick loop calls this every couple of seconds so a
    scrape always sees fresh numbers.  Never raises."""
    try:
        tracing.set_gauge("host_rss_bytes", float(host_rss_bytes()))
        for rec in device_snapshot():
            labels = {"device": rec["device"]}
            tracing.set_gauge_labeled("hbm_bytes_in_use", labels,
                                      float(rec["bytes_in_use"]))
            tracing.set_gauge_labeled("hbm_peak_bytes_in_use", labels,
                                      float(rec["peak_bytes_in_use"]))
            if rec["bytes_limit"]:
                tracing.set_gauge_labeled("hbm_bytes_limit", labels,
                                          float(rec["bytes_limit"]))
    except Exception:  # noqa: BLE001 — gauges are best-effort
        pass


def update_spool_gauge(spool_dir: str) -> None:
    """Export the spool volume's free bytes as the
    ``ict_spool_disk_free_bytes`` gauge — the figure the fleet alert
    pack's ``spool_disk_low`` rule watches (a daemon whose spool volume
    fills starts failing manifest writes, the
    ``service_spool_save_errors`` alarm's *leading* indicator).  Never
    raises; a missing directory just leaves the gauge unset."""
    try:
        tracing.set_gauge("spool_disk_free_bytes",
                          float(shutil.disk_usage(spool_dir or ".").free))
    except Exception:  # noqa: BLE001 — gauges are best-effort
        pass


def observe_route(route: str) -> None:
    """Record the device-memory high-water mark attributable to ``route``
    (stepwise / fused / chunked / sharded / sharded_batch): called right
    after a route finishes, while its peak is the freshest thing in
    ``peak_bytes_in_use``.  The gauge keeps the max ever seen per route —
    peaks are ratchets, not samples."""
    try:
        snap = device_snapshot()
        if not snap:
            return
        peak = max(rec["peak_bytes_in_use"] for rec in snap)
        in_use = max(rec["bytes_in_use"] for rec in snap)
        labels = {"route": route}
        tracing.max_gauge_labeled("route_hbm_peak_bytes", labels, float(peak))
        tracing.set_gauge_labeled("route_hbm_bytes_in_use", labels,
                                  float(in_use))
    except Exception:  # noqa: BLE001 — gauges are best-effort
        pass


# --- compiled-executable cost/memory analysis (XLA's static accounting) ---


def exec_analysis_enabled() -> bool:
    """Per-bucket executable analysis costs one extra AOT compile per shape
    bucket (amortised by the persistent compile cache the daemon enables);
    ``ICT_EXEC_ANALYSIS=0`` opts out for operators who want zero extra
    compiles near a scarce tunnel window."""
    return os.environ.get("ICT_EXEC_ANALYSIS", "1") != "0"


def executable_analysis(compiled) -> dict:
    """The JSON-ready facts from one ``jax.stages.Compiled``: FLOPs and
    bytes accessed from ``cost_analysis()``, the buffer-assignment split
    from ``memory_analysis()``.  Missing halves are omitted, not fatal —
    both surfaces vary by backend and jax version."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # older jax returns [dict]
            ca = ca[0] if ca else {}
        if ca:
            if "flops" in ca:
                out["flops"] = float(ca["flops"])
            if "bytes accessed" in ca:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:  # noqa: BLE001 — the other half may still land
        pass
    try:
        ma = compiled.memory_analysis()
        out["argument_bytes"] = int(ma.argument_size_in_bytes)
        out["output_bytes"] = int(ma.output_size_in_bytes)
        out["temp_bytes"] = int(ma.temp_size_in_bytes)
        out["generated_code_bytes"] = int(ma.generated_code_size_in_bytes)
        out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"])
    except Exception:  # noqa: BLE001 — cost half alone is still valuable
        pass
    return out


def note_executable(shape_bucket: str, compiled) -> dict:
    """Record one compiled executable's analysis under its shape-bucket
    label: registry (job manifests, bench payload) + labeled gauges
    (``/metrics``).  Re-noting a bucket overwrites — the analysis is a
    static fact of (shape, route), so the last writer agrees with every
    earlier one."""
    analysis = executable_analysis(compiled)
    if not analysis:
        return analysis
    _exec_registry[shape_bucket] = analysis
    labels = {"shape_bucket": shape_bucket}
    for key, family in (("bytes_accessed", "executable_bytes_accessed"),
                        ("flops", "executable_flops"),
                        ("temp_bytes", "executable_temp_bytes"),
                        ("peak_bytes", "executable_peak_bytes")):
        if key in analysis:
            tracing.set_gauge_labeled(family, labels, float(analysis[key]))
    return analysis


def executables_snapshot() -> dict[str, dict]:
    return {k: dict(v) for k, v in sorted(_exec_registry.items())}


def analyze_batch_route(batch_shape, cfg) -> dict | None:
    """Static analysis of the serving daemon's bucket executable — the
    vmapped fused loop at ``batch_shape`` = (batch, nsub, nchan, nbin) —
    memoized per shape bucket.  The AOT lower().compile() runs on the live
    backend (abstract avals, no device buffers), so on TPU the numbers
    reflect real fusion and buffer assignment; with the persistent compile
    cache on (the daemon default) the duplicate compile is mostly a disk
    read.  Returns the analysis dict, or None when disabled/failed."""
    if not exec_analysis_enabled() or not backend_live():
        return None
    bucket = tracing.shape_bucket_label(batch_shape)
    if bucket in _exec_registry:
        return _exec_registry[bucket]
    try:
        import jax
        import numpy as np

        from iterative_cleaner_tpu.parallel.sharded import batched_fused_clean

        b, nsub, nchan, nbin = (int(v) for v in batch_shape)
        D = jax.ShapeDtypeStruct((b, nsub, nchan, nbin), np.float32)
        w = jax.ShapeDtypeStruct((b, nsub, nchan), np.float32)
        v = jax.ShapeDtypeStruct((b, nsub, nchan), np.bool_)
        s = jax.ShapeDtypeStruct((), np.float32)
        with tracing.phase("exec_analysis"):
            compiled = batched_fused_clean.lower(
                D, w, v, s, s, max_iter=int(cfg.max_iter),
                pulse_region=tuple(cfg.pulse_region)).compile()
        return note_executable(bucket, compiled) or None
    except Exception:  # noqa: BLE001 — analysis is best-effort
        return None


def memory_report() -> dict:
    """The JSON block bench.py carries on every exit path and operators
    read off job manifests: host RSS, per-device HBM view, and every
    executable analysis recorded so far."""
    report: dict = {"host_rss_bytes": host_rss_bytes()}
    devices = device_snapshot()
    if devices:
        report["devices"] = devices
    execs = executables_snapshot()
    if execs:
        report["executables"] = execs
    return report


def reset_for_tests() -> None:
    _exec_registry.clear()
    _stats_unsupported.clear()
