"""obs — the structured-telemetry layer (docs/OBSERVABILITY.md).

What grew out of ``utils/tracing.py`` (which remains as a compatibility
shim over :mod:`.tracing`), organised as three pillars:

- :mod:`.events`    — trace context (``trace_id``/``span_id``) minted at
                      every entry point and a JSON-lines event log
                      (``--telemetry out.jsonl`` / ``ICT_TELEMETRY``);
- :mod:`.tracing`   — the process-global counter registry, now with fixed
                      log2-bucket latency histograms, error counters and
                      labeled counters, plus the jax compile listener;
- :mod:`.metrics`   — Prometheus text exposition over the registry (the
                      daemon's ``/metrics``; legacy JSON at
                      ``/metrics.json``);
- :mod:`.forensics` — convergence forensics: per-diagnostic zap
                      attribution and termination reasons.

Everything here is strictly read-only on the math: no hook ever touches a
mask, and every hook is a no-op when its sink is disabled, so the hot path
pays nothing (the fuzz corpus pins mask bit-identity with telemetry on).
"""

from iterative_cleaner_tpu.obs import events, forensics, metrics, tracing

__all__ = ["events", "forensics", "metrics", "tracing"]
