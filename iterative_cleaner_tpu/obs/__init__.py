"""obs — the structured-telemetry layer (docs/OBSERVABILITY.md).

What grew out of ``utils/tracing.py`` (which remains as a compatibility
shim over :mod:`.tracing`), organised as pillars:

- :mod:`.events`    — trace context (``trace_id``/``span_id``) minted at
                      every entry point and a JSON-lines event log
                      (``--telemetry out.jsonl`` / ``ICT_TELEMETRY``);
- :mod:`.tracing`   — the process-global counter registry, with fixed
                      log2-bucket latency histograms, error counters,
                      labeled counters and gauges, plus the jax compile
                      listener;
- :mod:`.metrics`   — Prometheus text exposition over the registry (the
                      daemon's ``/metrics``; legacy JSON at
                      ``/metrics.json``);
- :mod:`.forensics` — convergence forensics: per-diagnostic zap
                      attribution and termination reasons;
- :mod:`.flight`    — the always-on bounded flight-recorder ring of
                      recent events/phase timings, dumped on fault-ladder
                      trips / SIGTERM and served at ``GET /debug/flight``;
- :mod:`.profiling` — on-demand bounded ``jax.profiler`` captures
                      (``POST /debug/profile``, per-job capture) grown
                      from the ``trace_dir`` one-shot;
- :mod:`.memory`    — HBM / host-RSS / compiled-executable memory+cost
                      accounting: every ``memory_stats()`` read in the
                      tree, exported as gauges and JSON reports;
- :mod:`.audit`     — shadow-oracle parity auditing (``ICT_AUDIT_RATE``,
                      per-job opt-in), score ulp-drift accounting, and
                      divergence repro bundles replayed by
                      ``tools/replay_repro.py``;
- :mod:`.quality`   — RFI data-quality telemetry: zap fractions,
                      per-channel/per-subint occupancy histograms,
                      per-diagnostic attribution rates, termination mix.

Everything here is strictly read-only on the math: no hook ever touches a
mask, and every hook is a no-op when its sink is disabled, so the hot path
pays nothing (the fuzz corpus pins mask bit-identity with telemetry, the
flight recorder, and profiler capture on).
"""

from iterative_cleaner_tpu.obs import (
    audit,
    events,
    flight,
    forensics,
    memory,
    metrics,
    profiling,
    quality,
    tracing,
)

__all__ = ["audit", "events", "flight", "forensics", "memory", "metrics",
           "profiling", "quality", "tracing"]
