"""Flight recorder: an always-on bounded ring of recent events.

The telemetry event log (:mod:`.events`) answers "what happened" only when
an operator turned a sink on *before* the incident; the flight recorder
answers the post-mortem question — *what was the process doing just now* —
without any opt-in.  Every :func:`.events.emit` call (sink or no sink) and
every completed :func:`.tracing.observe_phase` lands here as one small
record in a fixed-size ring, so the cost is a dict build and a deque
append under a lock: bounded memory, no I/O, nothing on disk until a
:func:`dump` is asked for.

Dumps happen at exactly the moments guesswork used to start: the serving
daemon writes the ring on worker fault-ladder trips and on SIGTERM, and
serves it live at ``GET /debug/flight`` (docs/OBSERVABILITY.md).

Strictly read-only on the math — recording never touches a mask, and the
fuzz spot-check pins bit-identical masks with ``ICT_FLIGHT=1`` and a
profiler capture active.  ``ICT_FLIGHT=0`` disables recording entirely;
``ICT_FLIGHT_SIZE`` resizes the ring (default 512 events).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

DEFAULT_CAPACITY = 512

#: On-disk dumps kept per directory (oldest swept): a daemon riding a
#: flapping backend must not fill its spool with one dump per trip.
MAX_DUMPS_KEPT = 20

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=DEFAULT_CAPACITY)  # ict: guarded-by(_lock)


def enabled() -> bool:
    """Recording is ON unless explicitly disabled — the recorder exists for
    the incidents nobody predicted."""
    return os.environ.get("ICT_FLIGHT", "1") != "0"


def capacity() -> int:
    try:
        n = int(os.environ.get("ICT_FLIGHT_SIZE", DEFAULT_CAPACITY))
    except ValueError:
        return DEFAULT_CAPACITY
    return max(n, 1)


def note(event: str, **fields) -> None:
    """Append one record to the ring.  Never raises; values are kept as
    given and coerced to strings only at snapshot/dump time."""
    if not enabled():
        return
    rec = {"ts": round(time.time(), 6), "event": event}
    rec.update(fields)
    cap = capacity()
    with _lock:
        global _ring
        if _ring.maxlen != cap:
            _ring = collections.deque(_ring, maxlen=cap)
        _ring.append(rec)


def note_phase(name: str, seconds: float, error: bool = False) -> None:
    """The :func:`.tracing.observe_phase` hook — phase timings are the
    "what was it doing" half of a post-mortem (events are the "to whom")."""
    if not enabled():
        return
    rec = {"ts": round(time.time(), 6), "event": "phase", "phase": name,
           "duration_s": round(seconds, 6)}
    if error:
        rec["error"] = True
    cap = capacity()
    with _lock:
        global _ring
        if _ring.maxlen != cap:
            _ring = collections.deque(_ring, maxlen=cap)
        _ring.append(rec)


def snapshot() -> list[dict]:
    """Oldest-first copy of the ring (JSON-safe: values stringified the
    same way the event log's sink would)."""
    with _lock:
        recs = list(_ring)
    # Round-trip through json so a record carrying a non-serializable value
    # (an exception object, a numpy scalar) can never break /debug/flight.
    return json.loads(json.dumps(recs, default=str))


def reset() -> None:
    """Clear the ring (tests)."""
    with _lock:
        _ring.clear()


def dump(reason: str, directory: str) -> str | None:
    """Write the ring to ``<directory>/flight-<unixms>.json`` and sweep old
    dumps beyond :data:`MAX_DUMPS_KEPT`.  Returns the path, or None when
    recording is disabled or the write failed — a post-mortem aid must
    never become a second failure."""
    if not enabled():
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory,
                            f"flight-{int(time.time() * 1000):013d}.json")
        payload = {
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "events": snapshot(),
        }
        tmp = f"{path}.part"
        with open(tmp, "w") as fh:
            json.dump(payload, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)
        dumps = sorted(n for n in os.listdir(directory)
                       if n.startswith("flight-") and n.endswith(".json"))
        for name in dumps[:-MAX_DUMPS_KEPT]:
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass
        return path
    except Exception:  # noqa: BLE001 — best-effort by contract
        return None
