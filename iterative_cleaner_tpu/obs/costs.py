"""Cost & efficiency accounting: per-job device-time attribution and the
per-replica showback ledger (ISSUE 15).

The obs tower measures *health* (latency histograms, queue depths, HBM
peaks) but until this module nothing answered "who consumed what, and how
efficiently": tenants had quotas with zero usage metering, the result
caches counted ``bytes_saved`` attributed to no one, and the memoized
``exec_analysis`` static costs were never compared against achieved
throughput.  Three pieces, all read-only on the math:

- **CostRecord** — one dict per job (``Job.cost``, persisted on the spool
  manifest): device-seconds split by phase, compile-seconds, the static
  bytes/FLOPs model, the coalesced batch size it shared, cache-hit
  avoided cost, and a roofline attainment ratio.  The dispatch worker
  accumulates it (:func:`add_dispatch_share` / :func:`add_exec_share`)
  and finalizes it at the terminal transition (:func:`finalize`).
- **Attribution rules** — a coalesced batch's measured dispatch seconds
  (and its executable's static bytes/FLOPs) are apportioned EQUALLY
  across its K member jobs; a failed dispatch attempt's seconds are
  apportioned the same way (the jobs it retried for consumed the device).
  The load-bearing invariant, asserted by tests and the serve-fleet
  smoke: per replica, the summed attributed device-seconds equal
  Δ``ict_service_dispatch_s`` within 1% — the attributed shares are
  splits of the exact value :func:`obs.tracing.observe_phase` records,
  so conservation holds by construction, not by luck.
- **CostLedger** — the per-replica aggregate (by tenant, shape bucket,
  and route), RLock'd, spool-persisted (``<spool>/costs.json``,
  atomic-rename), restart-resumed.  Every :meth:`~CostLedger.record`
  also bumps the process-global ``ict_cost_*`` counters the fleet
  router's existing poll-tick scrape federates (fleet/costs.py) — zero
  new traffic.  Counters are per-process-life (pre-registered at 0 on
  daemon start, the PR 12 freeze-on-missing lesson); the ledger file is
  the durable lifetime record served at ``GET /costs``.

**Attainment** is the roofline-style efficiency figure: achieved bytes/s
(the executable's static ``bytes_accessed`` model over the measured
dispatch seconds) against a reference bandwidth — ``ICT_ROOFLINE_GBPS``
when the operator pins one, else the ingest pipeline's measured
effective GB/s (the bandwidth the host actually demonstrated).  A ratio
near 1 means the dispatch ran as fast as bytes could move; << 1 means
launch overhead or starvation (docs/OBSERVABILITY.md "Cost & efficiency
accounting").
"""

from __future__ import annotations

import json
import os
import threading

from iterative_cleaner_tpu.obs import tracing

#: Tenant label for jobs submitted without one (the fleet router's
#: X-ICT-Tenant convention, fleet/tenants.DEFAULT_TENANT — duplicated
#: here so obs/ never imports fleet/).
DEFAULT_TENANT = "default"

#: Shape-bucket label for records without a decoded shape (the
#: fleet/capacity.UNBUCKETED convention).
UNBUCKETED = "unbucketed"

#: The counter families the ledger renders (all low-cardinality labeled:
#: tenant names are operator-declared, buckets are shape classes, routes
#: a fixed set).  Pre-registered at 0 by :meth:`CostLedger.register_counters`
#: so gt-0 budget alerts can resolve across a clean replica restart
#: (PR 12's lazily-registered-series lesson).
TENANT_COUNTER_FAMILIES = (
    "cost_device_seconds_total",
    "cost_jobs_total",
    "cost_compile_seconds_total",
    "cost_bytes_accessed_total",
    "cost_cache_hits_total",
    "cost_cache_avoided_device_seconds_total",
    "cost_cache_avoided_bytes_total",
)

_ROOFLINE_ENV = "ICT_ROOFLINE_GBPS"


def reference_gbps() -> float | None:
    """The attainment reference bandwidth: ``ICT_ROOFLINE_GBPS`` when
    set (> 0), else the ingest pipeline's measured effective GB/s when
    it has moved bytes this process, else None (attainment unknowable —
    recorded as null, never guessed)."""
    env = os.environ.get(_ROOFLINE_ENV)
    if env:
        try:
            val = float(env)
            if val > 0:
                return val
        except ValueError:
            pass
    try:
        from iterative_cleaner_tpu.ingest import pipeline

        gbps = float(pipeline.stats_snapshot().get("effective_gbps", 0.0))
        return gbps if gbps > 0 else None
    except Exception:  # noqa: BLE001 — accounting is best-effort
        return None


def attainment_ratio(bytes_accessed, seconds, ref_gbps=None) -> float | None:
    """Achieved bytes/s over the reference bandwidth; None when either
    side is unknown or degenerate."""
    if ref_gbps is None:
        ref_gbps = reference_gbps()
    if not bytes_accessed or not ref_gbps or not seconds or seconds <= 0:
        return None
    return (float(bytes_accessed) / float(seconds)) / (float(ref_gbps) * 1e9)


def ensure(job) -> dict:
    """The job's CostRecord, initialized on first touch.  All WRITES
    happen on the dispatch-worker thread (one thread owns the device),
    but HTTP handler threads serialize the live Job concurrently
    (``dataclasses.asdict`` iterates these dicts), so every updater
    below follows the atomic-REBIND convention the other manifest
    containers use (exec_analysis, quality): copy via :func:`_mutable`,
    mutate the copy, assign ``job.cost`` once — a reader sees the old
    or the new record, never a dict changing size under iteration."""
    if not job.cost:
        job.cost = {
            "tenant": job.tenant or DEFAULT_TENANT,
            "bucket": UNBUCKETED,
            "route": "",
            "device_s": 0.0,
            "compile_s": 0.0,
            "bytes_accessed": 0.0,
            "flops": 0.0,
            "batch_k": 0,
            "attainment": None,
            "cache_hit": False,
            "avoided_device_s": 0.0,
            "avoided_bytes_accessed": 0.0,
            "phases": {},
        }
    return job.cost


def _mutable(job) -> dict:
    """A fresh copy of the job's record (phases dict included) for the
    copy-mutate-rebind update pattern ensure() documents."""
    cost = dict(ensure(job))
    cost["phases"] = dict(cost.get("phases", {}))
    return cost


def _add_phase(cost: dict, phase: str, seconds: float) -> None:
    phases = cost.setdefault("phases", {})
    phases[phase] = round(phases.get(phase, 0.0) + float(seconds), 6)


def add_phase(job, phase: str, seconds: float) -> None:
    """Accumulate one phase's wall seconds onto the job's record (the
    non-device phases: emit, oracle, cache_emit — the split the issue's
    "device-seconds split by phase" asks for rides in ``phases``)."""
    cost = _mutable(job)
    _add_phase(cost, phase, seconds)
    job.cost = cost


def add_dispatch_share(jobs, dispatch_s: float, compile_s: float = 0.0,
                       ) -> None:
    """Apportion one bucket dispatch's measured seconds (and the compile
    seconds the compile-accounting listener attributed to the window)
    equally across its K member jobs.  Called for FAILED attempts too —
    ``observe_phase('service_dispatch', ..., error=True)`` still counts
    the seconds, so conservation requires the attribution to as well."""
    if not jobs:
        return
    share = float(dispatch_s) / len(jobs)
    compile_share = float(compile_s) / len(jobs)
    for job in jobs:
        cost = _mutable(job)
        cost["device_s"] += share
        cost["compile_s"] += compile_share
        cost["batch_k"] = max(int(cost.get("batch_k", 0)), len(jobs))
        _add_phase(cost, "dispatch", share)
        job.cost = cost


def add_exec_share(jobs, analysis: dict, dispatch_s: float) -> float | None:
    """Apportion the batch executable's static cost model
    (obs/memory.analyze_batch_route: bytes accessed, FLOPs — figures for
    the WHOLE batch launch) across the K member jobs, and compute the
    batch's attainment ratio (exported as the
    ``ict_cost_attainment_ratio{shape_bucket}`` gauge and stamped on
    every member's record).  Returns the attainment, or None."""
    if not jobs or not analysis:
        return None
    k = len(jobs)
    bytes_total = float(analysis.get("bytes_accessed", 0.0) or 0.0)
    flops_total = float(analysis.get("flops", 0.0) or 0.0)
    attain = attainment_ratio(bytes_total, dispatch_s)
    bucket = UNBUCKETED
    for job in jobs:
        cost = _mutable(job)
        cost["bytes_accessed"] += bytes_total / k
        cost["flops"] += flops_total / k
        if attain is not None:
            cost["attainment"] = round(attain, 6)
        job.cost = cost
        if job.shape:
            bucket = tracing.shape_bucket_label(job.shape)
    if attain is not None:
        tracing.set_gauge_labeled("cost_attainment_ratio",
                                  {"shape_bucket": bucket}, float(attain))
    return attain


def add_cache_hit(job, origin_cost: dict | None) -> dict:
    """Mark a content-cache hit: zero device cost, the ORIGIN job's
    recorded figures as avoided cost (the issue's showback rule — the
    saving belongs to whoever would have paid the clean)."""
    cost = _mutable(job)
    cost["cache_hit"] = True
    origin_cost = origin_cost or {}
    cost["avoided_device_s"] = round(
        float(origin_cost.get("device_s", 0.0) or 0.0), 6)
    cost["avoided_bytes_accessed"] = float(
        origin_cost.get("bytes_accessed", 0.0) or 0.0)
    job.cost = cost
    return cost


def finalize(job) -> dict:
    """Stamp the identity fields (tenant / shape bucket / route) and
    round the float accumulators — called exactly once per job, right
    before the record lands in the ledger and on the manifest."""
    cost = _mutable(job)
    cost["tenant"] = job.tenant or DEFAULT_TENANT
    if job.shape:
        cost["bucket"] = tracing.shape_bucket_label(job.shape)
    cost["route"] = job.served_by or (
        "error" if job.state == "error" else "")
    if job.state == "error" and cost.get("cache_hit"):
        # A cache hit whose emission failed delivered nothing: counting
        # its avoided cost would over-report the tenant's savings.
        cost["cache_hit"] = False
        cost["avoided_device_s"] = 0.0
        cost["avoided_bytes_accessed"] = 0.0
    for key in ("device_s", "compile_s"):
        cost[key] = round(float(cost.get(key, 0.0)), 6)
    job.cost = cost
    return cost


def _zero_row() -> dict:
    return {"device_s": 0.0, "jobs": 0, "compile_s": 0.0,
            "bytes_accessed": 0.0, "flops": 0.0, "cache_hits": 0,
            "avoided_device_s": 0.0, "avoided_bytes": 0.0}


class CostLedger:
    """Per-replica cost aggregate (tenant / bucket / route), written by
    the dispatch-worker thread (:meth:`record`) and read by the HTTP
    handler threads (:meth:`report`); spool-persisted and
    restart-resumed, so the showback record survives replica restarts
    while the ``ict_cost_*`` counters stay per-process-life (the
    conservation invariant is a counter delta).  RLock, deliberately:
    the flush snapshot takes it lexically (the ICT007 discipline) while
    :meth:`record` already holds it."""

    def __init__(self, path: str = "", replica_id: str = "") -> None:
        self.path = path
        self.replica_id = replica_id
        self._lock = threading.RLock()
        self._tenants: dict[str, dict] = {}  # ict: guarded-by(self._lock)
        self._buckets: dict[str, dict] = {}  # ict: guarded-by(self._lock)
        self._routes: dict[str, dict] = {}  # ict: guarded-by(self._lock)
        self._totals: dict = _zero_row()  # ict: guarded-by(self._lock)
        self._dirty = False  # ict: guarded-by(self._lock)
        self._resumed = False  # ict: guarded-by(self._lock)
        if self.path:
            self._load()

    # --- persistence ---

    @staticmethod
    def _coerce_row(v) -> dict:
        """One resumed aggregate row with every field coerced to its
        numeric type (non-numeric values fall back to 0) — the
        JobSpool.get discipline: a hand-edited or foreign-tool
        costs.json that is valid JSON but schema-drifted must degrade
        to zeros, never plant a TypeError in the dispatch worker's
        later ``record`` arithmetic."""
        row = _zero_row()
        if isinstance(v, dict):
            for key, default in list(row.items()):
                try:
                    row[key] = type(default)(v.get(key, default))
                except (TypeError, ValueError):
                    pass
        return row

    def _load(self) -> None:
        try:
            with open(self.path) as fh:
                d = json.load(fh)
            if not isinstance(d, dict):
                return
        except (OSError, ValueError):
            return
        def table(name: str) -> dict:
            src = d.get(name)
            if not isinstance(src, dict):
                return {}
            return {str(k): self._coerce_row(v) for k, v in src.items()
                    if isinstance(v, dict)}

        with self._lock:
            self._tenants = table("tenants")
            self._buckets = table("buckets")
            self._routes = table("routes")
            self._totals = self._coerce_row(d.get("totals"))
            self._resumed = True

    def flush(self) -> None:
        """Persist the aggregates atomically (.part-rename, the spool
        manifest discipline) when anything changed since the last flush.
        Never raises — the ledger is accounting, the spool manifest
        stays the durable record of the jobs themselves."""
        if not self.path:
            return
        with self._lock:
            if not self._dirty:
                return
            body = json.dumps(self.report(), indent=1, default=str)
            self._dirty = False
        try:
            tmp = f"{self.path}.part"
            with open(tmp, "w") as fh:
                fh.write(body)
                fh.write("\n")
            os.replace(tmp, self.path)
        except OSError:
            with self._lock:
                self._dirty = True   # retry on the next flush cadence

    # --- registration (daemon/router start) ---

    def register_counters(self) -> None:
        """Pre-register every ``ict_cost_*`` family at 0 so they are
        PRESENT on the exposition from the first scrape: the fleet's
        budget-burn alerts are gt thresholds over these series, and a
        lazily-registered counter vanishing across a clean restart would
        let freeze-on-missing pin a fired alert forever (the PR 12
        lesson, applied before the bug this time)."""
        for family in TENANT_COUNTER_FAMILIES:
            tracing.count_labeled(family, {"tenant": DEFAULT_TENANT}, 0.0)
        tracing.count_labeled("cost_bucket_device_seconds_total",
                              {"shape_bucket": UNBUCKETED}, 0.0)
        tracing.count_labeled("cost_route_device_seconds_total",
                              {"route": "sharded"}, 0.0)
        tracing.set_gauge_labeled("cost_attainment_ratio",
                                  {"shape_bucket": UNBUCKETED}, 0.0)

    # --- the write path (dispatch-worker thread) ---

    def record(self, cost: dict) -> None:
        """Fold one finalized CostRecord into the aggregates and bump
        the ``ict_cost_*`` counters the fleet federation scrapes."""
        tenant = str(cost.get("tenant") or DEFAULT_TENANT)
        bucket = str(cost.get("bucket") or UNBUCKETED)
        route = str(cost.get("route") or "unknown")
        device_s = float(cost.get("device_s", 0.0) or 0.0)
        compile_s = float(cost.get("compile_s", 0.0) or 0.0)
        nbytes = float(cost.get("bytes_accessed", 0.0) or 0.0)
        flops = float(cost.get("flops", 0.0) or 0.0)
        hit = bool(cost.get("cache_hit", False))
        avoided_s = float(cost.get("avoided_device_s", 0.0) or 0.0)
        avoided_b = float(cost.get("avoided_bytes_accessed", 0.0) or 0.0)
        with self._lock:
            for row in (self._tenants.setdefault(tenant, _zero_row()),
                        self._buckets.setdefault(bucket, _zero_row()),
                        self._routes.setdefault(route, _zero_row()),
                        self._totals):
                row["device_s"] = round(row["device_s"] + device_s, 6)
                row["jobs"] += 1
                row["compile_s"] = round(row["compile_s"] + compile_s, 6)
                row["bytes_accessed"] += nbytes
                row["flops"] += flops
                if hit:
                    row["cache_hits"] += 1
                    row["avoided_device_s"] = round(
                        row["avoided_device_s"] + avoided_s, 6)
                    row["avoided_bytes"] += avoided_b
            self._dirty = True
        labels = {"tenant": tenant}
        tracing.count_labeled("cost_device_seconds_total", labels, device_s)
        tracing.count_labeled("cost_jobs_total", labels)
        tracing.count_labeled("cost_compile_seconds_total", labels,
                              compile_s)
        tracing.count_labeled("cost_bytes_accessed_total", labels, nbytes)
        if hit:
            tracing.count_labeled("cost_cache_hits_total", labels)
            tracing.count_labeled("cost_cache_avoided_device_seconds_total",
                                  labels, avoided_s)
            tracing.count_labeled("cost_cache_avoided_bytes_total", labels,
                                  avoided_b)
        tracing.count_labeled("cost_bucket_device_seconds_total",
                              {"shape_bucket": bucket}, device_s)
        tracing.count_labeled("cost_route_device_seconds_total",
                              {"route": route}, device_s)

    # --- reads (HTTP handler threads, tests, bench) ---

    def device_seconds(self) -> float:
        with self._lock:
            return float(self._totals["device_s"])

    def report(self) -> dict:
        """The lifetime showback view (``GET /costs`` on the replica):
        per-tenant / bucket / route rows plus the totals.  ``resumed``
        says whether a previous life's figures are folded in — the
        reason these totals may exceed this life's counters."""
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "resumed": self._resumed,
                "totals": dict(self._totals),
                "tenants": {k: dict(v)
                            for k, v in sorted(self._tenants.items())},
                "buckets": {k: dict(v)
                            for k, v in sorted(self._buckets.items())},
                "routes": {k: dict(v)
                           for k, v in sorted(self._routes.items())},
            }
