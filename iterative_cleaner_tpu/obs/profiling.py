"""On-demand ``jax.profiler`` capture — the ``trace_dir`` one-shot grown
into a facility.

The CLI's ``--trace DIR`` (config.trace_dir) wraps one run in one trace and
exits; a long-lived daemon needs the opposite shape: start a *bounded*
capture around whatever is in flight right now (``POST /debug/profile``),
list the artifacts later (``GET /debug/profiles``), and capture one
specific job's dispatch when the submitter asked for it
(``POST /jobs {"path": ..., "profile": true}`` — the artifact directory is
recorded on the job's spool manifest).  View artifacts with tensorboard or
xprof, exactly like the one-shot's.

The TSL profiler behind ``jax.profiler.start_trace`` is process-global and
refuses to nest, so one lock serializes every capture in the process: a
second ``POST /debug/profile`` gets 409, and a per-job capture that finds
the profiler busy skips silently (noted on the flight recorder) rather
than failing the job.  Every timed capture is bounded
(:func:`max_capture_s`, default 60 s) — an operator typo must not leave a
daemon writing trace events forever.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from iterative_cleaner_tpu.obs import flight

DEFAULT_MAX_CAPTURE_S = 60.0

_lock = threading.Lock()          # held only to mutate _active, never I/O
_active: dict | None = None       # {"dir", "started_s", "until_s", "timer"}  # ict: guarded-by(_lock)


def max_capture_s() -> float:
    try:
        v = float(os.environ.get("ICT_PROFILE_MAX_S", DEFAULT_MAX_CAPTURE_S))
    except ValueError:
        return DEFAULT_MAX_CAPTURE_S
    return v if v > 0 else DEFAULT_MAX_CAPTURE_S


def active() -> dict | None:
    """The in-flight capture (dir / started_s / until_s), or None."""
    with _lock:
        if _active is None:
            return None
        return {k: _active[k] for k in ("dir", "started_s", "until_s")}


def start(root: str, duration_s: float = 5.0, tag: str = "capture") -> dict:
    """Begin a bounded capture into a fresh directory under ``root``; a
    timer stops it after ``duration_s`` (clamped to :func:`max_capture_s`)
    unless :func:`stop` is called first.  Raises RuntimeError when a
    capture is already running — the profiler is process-global."""
    duration_s = min(max(float(duration_s), 0.1), max_capture_s())
    out_dir = os.path.join(
        root, f"{int(time.time() * 1000):013d}-{tag}")
    with _lock:
        global _active
        if _active is not None:
            raise RuntimeError(
                f"a profiler capture is already running ({_active['dir']}); "
                "stop it or wait for its deadline")
        os.makedirs(out_dir, exist_ok=True)
        import jax

        jax.profiler.start_trace(out_dir)
        timer = threading.Timer(duration_s, _deadline_stop, args=(out_dir,))
        timer.daemon = True
        now = time.time()
        _active = {"dir": out_dir, "started_s": now,
                   "until_s": now + duration_s, "timer": timer}
        timer.start()
    flight.note("profile_start", dir=out_dir, duration_s=duration_s)
    return {"dir": out_dir, "duration_s": duration_s}


def stop(expected_dir: str | None = None) -> dict | None:
    """End the running capture; returns its record or None when idle.

    ``expected_dir`` makes the stop an *ownership-checked* one: a caller
    whose capture may have already been ended by the deadline timer (the
    per-job ``maybe_capture``, the timer itself) passes the dir it
    started, and a mismatch no-ops — otherwise a late finally/timer would
    truncate an unrelated capture an operator started in the meantime."""
    with _lock:
        global _active
        if _active is None:
            return None
        if expected_dir is not None and _active["dir"] != expected_dir:
            return None
        rec = _active
        _active = None
        rec["timer"].cancel()
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 — a failed stop must not
            # wedge the facility: the state is cleared either way, and the
            # failure is on the flight record for the post-mortem.
            flight.note("profile_stop_failed", dir=rec["dir"],
                        error=repr(exc))
            return {"dir": rec["dir"], "error": repr(exc)}
    flight.note("profile_stop", dir=rec["dir"],
                duration_s=round(time.time() - rec["started_s"], 3))
    return {"dir": rec["dir"],
            "duration_s": round(time.time() - rec["started_s"], 3)}


def _deadline_stop(out_dir: str) -> None:
    stop(expected_dir=out_dir)


@contextlib.contextmanager
def maybe_capture(root: str, tag: str, want: bool = True):
    """Per-job capture around a block: yields the artifact directory, or
    None when not wanted / the profiler is busy (skipped, never queued —
    the job's latency contract beats its optional profile)."""
    if not want:
        yield None
        return
    try:
        rec = start(root, duration_s=max_capture_s(), tag=tag)
    except RuntimeError:
        flight.note("profile_skipped_busy", tag=tag)
        yield None
        return
    except Exception as exc:  # noqa: BLE001 — profiling is best-effort
        flight.note("profile_start_failed", tag=tag, error=repr(exc))
        yield None
        return
    try:
        yield rec["dir"]
    finally:
        stop(expected_dir=rec["dir"])


def list_profiles(root: str) -> list[dict]:
    """Artifact directories under ``root`` (newest first): name, total
    bytes, file count, mtime — enough to pick one to download."""
    out = []
    try:
        names = sorted(os.listdir(root), reverse=True)
    except OSError:
        return out
    for name in names:
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        nbytes = nfiles = 0
        mtime = 0.0
        for dirpath, _dirs, files in os.walk(path):
            for f in files:
                try:
                    st = os.stat(os.path.join(dirpath, f))
                except OSError:
                    continue
                nbytes += st.st_size
                nfiles += 1
                mtime = max(mtime, st.st_mtime)
        out.append({"name": name, "bytes": nbytes, "files": nfiles,
                    "mtime": round(mtime, 3)})
    return out


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """The original one-shot (config.trace_dir / CLI ``--trace``): a
    jax.profiler trace around a block when ``trace_dir`` is set, no-op
    otherwise.  Lives here with the rest of the capture machinery;
    :mod:`.tracing` re-exports it for its historical import sites."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
