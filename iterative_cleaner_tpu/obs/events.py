"""Trace context + the JSON-lines telemetry event log.

A ``trace_id`` is minted at every entry point (CLI run, POST /jobs, online
session) and threaded through every layer a request crosses — scheduler
admission, worker dispatch, chunked/sharded execution, online block ingest
— so an operator can reconstruct any job's full path from one grep of the
event log.  Propagation is explicit where work crosses threads (the id
rides on the Job / session manifest) and implicit within a thread (a
contextvar, set by :func:`trace_scope` / :func:`span`, that nested
:func:`emit` calls inherit).

The sink is a JSON-lines file: ``--telemetry out.jsonl`` on the CLI and
the serving daemon, or the ``ICT_TELEMETRY`` environment variable.  One
event per line: ``{"ts": ..., "event": ..., "trace_id": ...,
"span_id": ..., ...fields}``.  When no sink is configured every hook here
is a cheap no-op — the hot path pays a single ``if``.

Ids are random hex (16 chars trace / 8 chars span), not time-derived:
they only need to be grep-unique within one log, and minting must stay
nanosecond-cheap on the disabled path too (POST /jobs echoes the id even
with the log off).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import sys
import threading
import time
import uuid
from dataclasses import dataclass

from iterative_cleaner_tpu.obs import flight


@dataclass(frozen=True)
class TraceContext:
    trace_id: str
    span_id: str = ""


_current: contextvars.ContextVar[TraceContext | None] = contextvars.ContextVar(
    "ict_trace_ctx", default=None)

_UNSET = object()
_explicit = _UNSET          # configure() override; _UNSET -> read the env  # ict: guarded-by(_lock)
_lock = threading.Lock()
_fh = None                  # cached append handle for the active path  # ict: guarded-by(_lock)
_fh_path: str | None = None  # ict: guarded-by(_lock)
_warned = False  # ict: guarded-by(_lock)
_retry_at = 0.0             # sink-failure backoff deadline (monotonic)  # ict: guarded-by(_lock)
_fh_size = 0                # bytes in the active sink file (tracked, not stat-ed per emit)  # ict: guarded-by(_lock)
_rotations = 0              # size-cap rotations this process has performed  # ict: guarded-by(_lock)

#: After a failed sink write, drop events for this long, then try again —
#: transient disk trouble (brief ENOSPC, a remounted log volume) must not
#: silence a weeks-lived daemon's event log forever.
SINK_RETRY_S = 60.0

#: Default size cap (MB) on the sink file before it rotates to
#: ``<path>.1`` (one rotated generation, so the disk footprint is bounded
#: at ~2x the cap); ``ICT_EVENT_LOG_MAX_MB`` overrides, 0 disables
#: rotation entirely.  Rotation is a close + rename + reopen inside the
#: emit path's existing OSError envelope — it can never block or raise.
EVENT_LOG_MAX_MB = 256


def _max_bytes() -> int:
    try:
        mb = float(os.environ.get("ICT_EVENT_LOG_MAX_MB", EVENT_LOG_MAX_MB))
    except ValueError:
        mb = EVENT_LOG_MAX_MB
    return int(mb * (1 << 20)) if mb > 0 else 0


def rotations() -> int:
    """Size-cap rotations performed by this process (tests, /healthz)."""
    with _lock:
        return _rotations


def sink_degraded() -> bool:
    """True while the sink sits in its post-failure drop window (a write
    failed — full disk, yanked directory — and events are being dropped
    until the ``SINK_RETRY_S`` backoff expires).  The proving ground's
    full-disk chaos drill exports this as the ``ict_prove_event_sink_``
    ``degraded`` gauge so the fault is alertable instead of a lone stderr
    warning; :func:`configure` (pointing at a healthy path) clears it
    immediately."""
    with _lock:
        return bool(_retry_at) and time.monotonic() < _retry_at


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    return uuid.uuid4().hex[:8]


def configure(path: str | None) -> None:
    """Point the event log at ``path`` (None/'' disables and, for tests,
    returns to honoring ``ICT_TELEMETRY``).  The file is opened lazily in
    append mode on first emit."""
    global _explicit, _fh, _fh_path, _retry_at
    with _lock:
        _explicit = path if path else _UNSET
        _retry_at = 0.0
        if _fh is not None and _fh_path != _sink_path_locked():
            try:
                _fh.close()
            except OSError:
                pass
            _fh = None
            _fh_path = None


def _sink_path_locked() -> str | None:
    if _explicit is _UNSET:
        return os.environ.get("ICT_TELEMETRY") or None
    return _explicit


def configured_sink() -> str | None:
    """The explicitly :func:`configure`-d JSON-lines sink path, or None
    when disabled / deferring to ``ICT_TELEMETRY``.  The in-process
    replica factory (fleet/autoscale.py) reads this so a replica spawned
    MID-RUN inherits the router's sink instead of resetting the
    process-global configuration out from under it."""
    with _lock:
        return None if _explicit is _UNSET else _explicit


def enabled() -> bool:
    """Whether an event sink is active (the one check every hook makes)."""
    if _explicit is _UNSET:
        return bool(os.environ.get("ICT_TELEMETRY"))
    return _explicit is not None


def active() -> bool:
    """Whether ANY consumer of :func:`emit` exists: the JSON-lines sink OR
    the always-on flight recorder (:mod:`.flight`, which mirrors every
    event into its bounded ring).  Call-site guards that only exist to
    skip building kwargs should use this, not :func:`enabled` — with the
    flight recorder on by default, an event skipped "because no sink" is
    an event missing from the post-mortem."""
    return enabled() or flight.enabled()


def current() -> TraceContext | None:
    return _current.get()


def current_trace_id() -> str:
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else ""


@contextlib.contextmanager
def trace_scope(trace_id: str, span_id: str = ""):
    """Bind a trace context to this thread/task so nested :func:`emit` and
    :func:`span` calls inherit it — the bridge for ids that crossed a
    thread boundary riding on a Job or session manifest."""
    token = _current.set(TraceContext(trace_id, span_id))
    try:
        yield
    finally:
        _current.reset(token)


def emit(event: str, trace_id: str | None = None, span_id: str | None = None,
         **fields) -> None:
    """Append one event line.  No-op without a sink; never raises — a
    failing sink (full disk, yanked directory) drops events for
    ``SINK_RETRY_S`` with one stderr warning, then tries again, rather
    than failing the clean it was observing or going silent forever."""
    global _fh, _fh_path, _warned, _retry_at, _fh_size, _rotations
    ctx = _current.get()
    tid = trace_id if trace_id is not None else (ctx.trace_id if ctx else "")
    sid = span_id if span_id is not None else (ctx.span_id if ctx else "")
    # Mirror every event into the always-on flight ring FIRST (bounded,
    # no I/O, independent of the sink): the recorder's whole point is the
    # incident nobody configured telemetry for.
    flight.note(event, trace_id=tid, **fields)
    if not enabled():
        return
    rec = {
        "ts": round(time.time(), 6),
        "event": event,
        "trace_id": tid,
        "span_id": sid,
    }
    rec.update(fields)
    line = json.dumps(rec, default=str) + "\n"
    with _lock:
        path = _sink_path_locked()
        if path is None:
            return
        if _retry_at and time.monotonic() < _retry_at:
            return
        try:
            if _fh is None or _fh_path != path:
                if _fh is not None:
                    _fh.close()
                _fh = open(path, "a")
                _fh_path = path
                # Size is tracked, not stat-ed per emit: seeded from the
                # file once at open, advanced by the bytes we write
                # (json.dumps is ensure_ascii, so len(line) IS the byte
                # count) — append-mode tell() semantics never enter it.
                _fh_size = os.path.getsize(path)
            cap = _max_bytes()
            if cap and _fh_size + len(line) > cap:
                # Size-cap rotation (ICT_EVENT_LOG_MAX_MB): the current
                # file becomes <path>.1 (replacing the previous rotated
                # generation — disk stays bounded at ~2x the cap) and the
                # sink continues into a fresh file.  A close + rename +
                # reopen under the lock we already hold; any failure
                # lands in the OSError envelope below, so rotation can
                # degrade to the normal drop-and-retry backoff but never
                # block or break the emit path.
                _fh.close()
                os.replace(path, path + ".1")
                _fh = open(path, "a")
                _fh_size = 0
                _rotations += 1
            _fh.write(line)
            _fh.flush()
            _fh_size += len(line)
            _retry_at = 0.0
        except OSError as exc:
            _retry_at = time.monotonic() + SINK_RETRY_S
            try:
                if _fh is not None:
                    _fh.close()
            except OSError:
                pass
            _fh = None
            _fh_path = None
            if not _warned:
                _warned = True
                print(f"warning: telemetry sink {path!r} failed ({exc}); "
                      f"dropping events, retrying every {SINK_RETRY_S:.0f}s",
                      file=sys.stderr)


@contextlib.contextmanager
def span(name: str, trace_id: str | None = None, **fields):
    """Emit ``<name>_start`` / ``<name>_end`` events around a block and bind
    the span's context: nested :func:`emit` calls inherit the trace_id and
    this span's id as their ``span_id``, and nested *spans* record it as
    their ``parent_span_id`` (the span's own start/end events carry both).
    The end event records ``duration_s`` and ``status`` ("ok"/"error").
    Fast no-op when neither the sink nor the flight recorder is active."""
    if not active():
        yield
        return
    ctx = _current.get()
    tid = trace_id if trace_id is not None else (ctx.trace_id if ctx else
                                                new_trace_id())
    sid = new_span_id()
    parent = ctx.span_id if ctx else ""
    emit(f"{name}_start", trace_id=tid, span_id=sid,
         parent_span_id=parent, **fields)
    token = _current.set(TraceContext(tid, sid))
    t0 = time.perf_counter()
    status = "ok"
    try:
        yield
    except BaseException:
        status = "error"
        raise
    finally:
        _current.reset(token)
        emit(f"{name}_end", trace_id=tid, span_id=sid,
             parent_span_id=parent, status=status,
             duration_s=round(time.perf_counter() - t0, 6))
