"""Shadow-oracle parity auditing + divergence repro bundles.

The repo's load-bearing invariant — flag masks bit-identical to the numpy
oracle in every execution mode (CLAUDE.md) — is verified offline by tier-1
tests and ``tools/fuzz_sweep.py``; nothing watched it *in production*.
This module closes that gap:

- :func:`run_audit` replays one finished clean's inputs through the numpy
  oracle and compares: masks bit-for-bit (any difference is a
  **divergence**), float scores against the documented ~5e-5-relative
  envelope (:data:`AUDIT_DRIFT_BOUND` — the chunked-partial-block and
  incremental-template routes, docs/SCALING.md; every other route is
  bit-exact and trivially inside it).  Results land in the
  :mod:`.tracing` registries (``ict_audit_*`` on ``/metrics``, with a
  per-route drift histogram) and in a JSON-safe record.
- :class:`ShadowAuditor` is the serving daemon's low-priority background
  thread: the worker offers a sampled fraction of completed jobs
  (``ICT_AUDIT_RATE``, default 0; a per-job ``"audit": true`` at submit
  always audits) into a small bounded queue — a full queue *skips* the
  audit (counted) rather than holding decoded cubes hostage — and audit
  results are re-persisted onto the job's spool manifest.
- :func:`write_repro_bundle` captures everything a divergence needs to be
  re-run anywhere — input cube npz, config, versions, trace context,
  flight-ring dump — as one directory under ``<spool>/repro/`` (shared by
  the auditor, the CLI's ``--audit``, and ``tools/fuzz_sweep.py``);
  ``tools/replay_repro.py`` re-executes a bundle against both backends to
  confirm or clear the divergence.

Strictly read-only on the math: the audit replays *copies* of inputs
after the job already served its result, and a disabled auditor costs the
hot path one ``if``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import platform
import queue
import random
import sys
import threading
import time
import uuid

import numpy as np

from iterative_cleaner_tpu.obs import events, flight, tracing

#: The documented score-drift envelope (CLAUDE.md, docs/SCALING.md):
#: float scores may differ from the oracle by a few ulps — up to ~5e-5
#: relative — on the chunked-partial-block and incremental-template
#: routes; masks are bit-identical everywhere.
AUDIT_DRIFT_BOUND = 5e-5

#: Cumulative relative-drift histogram bounds (``le`` labels on
#: ``ict_audit_drift_total{route=...}``); the last finite bound is the
#: documented envelope, so "anything beyond the bound" is exactly the
#: +Inf-minus-last-bucket residue an alert watches.
DRIFT_BOUNDS: tuple[float, ...] = (0.0, 1e-7, 1e-6, 1e-5, AUDIT_DRIFT_BOUND)

#: Repro bundles kept per directory (oldest swept) — same rationale as
#: flight.MAX_DUMPS_KEPT: a systematically-diverging route must not fill
#: the spool with one cube-sized bundle per job.
MAX_BUNDLES_KEPT = 20

#: Mask-difference coordinates recorded verbatim on the audit record
#: (beyond this, the bundle's arrays are the record).
MAX_DIFF_COORDS = 16

_STOP = object()


def audit_rate(default: float = 0.0) -> float:
    """The sampling fraction from ``ICT_AUDIT_RATE``, clamped to [0, 1];
    0 (the default) disables sampling — per-job requests still audit."""
    env = os.environ.get("ICT_AUDIT_RATE")
    if env is None:
        return default
    try:
        val = float(env)
    except ValueError:
        print(f"warning: ignoring unparseable ICT_AUDIT_RATE={env!r} "
              "(want a fraction in [0, 1])", file=sys.stderr)
        return default
    return min(max(val, 0.0), 1.0)


def should_audit(requested: bool, rate: float) -> bool:
    """Per-job opt-in always audits; otherwise sample at ``rate``."""
    if requested:
        return True
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return random.random() < rate


def oracle_config(cfg):
    """The numpy-oracle replay config for ``cfg``: same algorithm
    parameters, jax-only routing flags stripped (CleanConfig validation
    rejects them with backend='numpy'), and ``audit`` off so a replay can
    never recurse."""
    return cfg.replace(backend="numpy", fused=False, pallas=False,
                       sharded_batch=False, stream=False, chunk_block=0,
                       audit=False)


def run_audit(D, w0, cfg, weights_served, scores_served=None, route="",
              oracle_result=None):
    """Replay one clean through the numpy oracle and compare.

    ``weights_served`` is the FINAL mask the caller emitted (bad-parts
    sweep included when the route applies it — the oracle side runs the
    same :func:`..parallel.batch.finalize_weights`); ``scores_served`` the
    route's last-iteration test scores, or None to skip the drift check.
    ``oracle_result`` lets a caller that already ran the oracle (bench's
    parity gate) skip the second replay.

    Returns ``(record, oracle_weights)``: a JSON-safe audit record, and
    the oracle's finalized weights (for bundle writers).  Counters:
    ``audit_runs`` always, ``audit_divergences`` + the
    ``audit_last_divergence_ts`` gauge on a mask mismatch,
    ``audit_drift_exceeded`` on scores beyond the documented bound, and
    one ``audit_drift_total{route,le}`` histogram observation.
    """
    from iterative_cleaner_tpu.core.cleaner import clean_cube
    from iterative_cleaner_tpu.parallel.batch import finalize_weights

    t0 = time.perf_counter()
    cfg_np = oracle_config(cfg)
    res_np = oracle_result
    if res_np is None:
        res_np = clean_cube(np.asarray(D), np.asarray(w0), cfg_np)
    oracle_w, _rfi = finalize_weights(res_np.weights, cfg_np)

    served = np.asarray(weights_served)
    diff = served != oracle_w
    n_diffs = int(diff.sum())
    record: dict = {
        "ts": round(time.time(), 3),
        "route": route,
        "mask_identical": n_diffs == 0,
        "n_mask_diffs": n_diffs,
        "oracle_loops": int(res_np.loops),
        "drift_bound": AUDIT_DRIFT_BOUND,
    }
    if n_diffs:
        coords = np.argwhere(diff)[:MAX_DIFF_COORDS]
        record["mask_diff_coords"] = [[int(i), int(j)] for i, j in coords]

    max_rel = None
    finite_mismatch = 0
    if scores_served is not None and res_np.test_results is not None:
        a = np.asarray(scores_served, np.float64)
        b = np.asarray(res_np.test_results, np.float64)
        fin = np.isfinite(a) & np.isfinite(b)
        # A score finite on one side and not the other is a structural
        # disagreement no relative tolerance covers — counted, and it
        # fails the bound.
        finite_mismatch = int(np.sum(np.isfinite(a) != np.isfinite(b)))
        max_rel = 0.0
        if fin.any():
            # Unit-floored drift: relative above |score| = 1, absolute
            # below it.  Scores are threshold-scaled (a zap decision fires
            # at |score| >= 1), so sub-unit magnitudes measure absolutely
            # — a 3e-6 wobble on a 0.03 score is a harmless few ulps, not
            # a 1e-4 "relative" excursion; at and above the decision
            # scale the measure is the documented relative envelope.
            max_rel = float(np.max(np.abs(a[fin] - b[fin])
                                   / np.maximum(np.abs(b[fin]), 1.0)))
        record["max_score_drift"] = max_rel
        record["score_finite_mismatch"] = finite_mismatch
    within = (finite_mismatch == 0
              and (max_rel is None or max_rel <= AUDIT_DRIFT_BOUND))
    record["drift_within_bound"] = within
    record["duration_s"] = round(time.perf_counter() - t0, 3)

    tracing.count("audit_runs")
    if max_rel is not None:
        # Cumulative ``le`` buckets (genuine Prometheus histogram
        # semantics: every bucket >= the value increments, +Inf always) —
        # "beyond the bound" is exactly +Inf minus the last finite bucket.
        route_lbl = route or "unknown"
        for bound in DRIFT_BOUNDS:
            if max_rel <= bound:
                tracing.count_labeled(
                    "audit_drift_total",
                    {"route": route_lbl, "le": repr(float(bound))})
        tracing.count_labeled("audit_drift_total",
                              {"route": route_lbl, "le": "+Inf"})
    if not within:
        tracing.count("audit_drift_exceeded")
    if n_diffs:
        tracing.count("audit_divergences")
        tracing.set_gauge("audit_last_divergence_ts", time.time())
    return record, oracle_w


def audit_report() -> dict:
    """The cumulative audit counters as one JSON block — ``/healthz``'s
    audit fields, ``GET /debug/audit``'s header, and the ``audit`` block
    bench.py carries on every exit path."""
    snap = tracing.counters_snapshot()
    gauges, _ = tracing.gauges_snapshot()
    return {
        "rate": audit_rate(),
        "audits_run": int(snap.get("audit_runs", 0)),
        "divergences": int(snap.get("audit_divergences", 0)),
        "drift_exceeded": int(snap.get("audit_drift_exceeded", 0)),
        "skipped": int(snap.get("audit_skipped", 0)),
        "last_divergence_ts": float(
            gauges.get("audit_last_divergence_ts", 0.0)),
    }


# --- divergence repro bundles ---


def default_repro_dir() -> str:
    """Bundle directory for non-daemon callers (CLI ``--audit``, the fuzz
    sweep); the daemon uses ``<spool>/repro``."""
    return os.environ.get("ICT_REPRO_DIR") or "./ict_repro"


def write_repro_bundle(directory: str, *, D, w0, cfg, reason: str,
                       weights_served=None, weights_oracle=None,
                       scores_served=None, trace_id: str = "",
                       job_id: str = "", route: str = "",
                       record: dict | None = None) -> str | None:
    """Write one self-contained divergence bundle under ``directory``.

    Layout: ``repro-<unixms>-<hex6>/`` holding ``arrays.npz`` (the input
    cube + weights, plus whatever masks/scores the caller has),
    ``manifest.json`` (reason, config, versions, trace context, the audit
    record), and ``flight.json`` (the in-process flight ring at write
    time).  The directory is built under a ``.part`` name and renamed, so
    a half-written bundle is never mistaken for a replayable one; old
    bundles beyond :data:`MAX_BUNDLES_KEPT` are swept.  Returns the bundle
    path, or None on failure — a forensics aid must never become a second
    failure."""
    try:
        os.makedirs(directory, exist_ok=True)
        name = f"repro-{int(time.time() * 1000):013d}-{uuid.uuid4().hex[:6]}"
        final = os.path.join(directory, name)
        tmp = f"{final}.part"
        os.makedirs(tmp)
        arrays = {"D": np.asarray(D), "w0": np.asarray(w0)}
        if weights_served is not None:
            arrays["weights_served"] = np.asarray(weights_served)
        if weights_oracle is not None:
            arrays["weights_oracle"] = np.asarray(weights_oracle)
        if scores_served is not None:
            arrays["scores_served"] = np.asarray(scores_served)
        np.savez_compressed(os.path.join(tmp, "arrays.npz"), **arrays)
        jax_mod = sys.modules.get("jax")  # never import-init for a bundle
        from iterative_cleaner_tpu import __version__

        manifest = {
            "reason": reason,
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "trace_id": trace_id,
            "job_id": job_id,
            "route": route,
            "config": dataclasses.asdict(cfg),
            "arrays": sorted(arrays),
            "record": record or {},
            "versions": {
                "iterative_cleaner_tpu": __version__,
                "numpy": np.__version__,
                "jax": getattr(jax_mod, "__version__", None),
                "python": platform.python_version(),
            },
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as fh:
            json.dump(manifest, fh, indent=1, default=str)
            fh.write("\n")
        with open(os.path.join(tmp, "flight.json"), "w") as fh:
            json.dump({"events": flight.snapshot()}, fh, indent=1)
            fh.write("\n")
        os.replace(tmp, final)
        bundles = sorted(n for n in os.listdir(directory)
                         if n.startswith("repro-")
                         and not n.endswith(".part"))
        for old in bundles[:-MAX_BUNDLES_KEPT]:
            _rmtree_quiet(os.path.join(directory, old))
        return final
    except Exception:  # noqa: BLE001 — best-effort by contract
        return None


def _rmtree_quiet(path: str) -> None:
    import shutil

    try:
        shutil.rmtree(path)
    except OSError:
        pass


def load_repro_bundle(path: str) -> tuple[dict, dict]:
    """Read a bundle back: ``(manifest, arrays)``.  Raises on a missing or
    malformed bundle — the replay tool turns that into its usage error."""
    with open(os.path.join(path, "manifest.json")) as fh:
        manifest = json.load(fh)
    with np.load(os.path.join(path, "arrays.npz")) as z:
        arrays = {k: z[k] for k in z.files}
    return manifest, arrays


def config_from_manifest(manifest: dict):
    """Rebuild the CleanConfig a bundle recorded (unknown / drifted keys
    dropped, so an old bundle replays on a newer tree)."""
    from iterative_cleaner_tpu.config import CleanConfig

    raw = manifest.get("config") or {}
    known = {f.name for f in dataclasses.fields(CleanConfig)}
    return CleanConfig(**{k: v for k, v in raw.items() if k in known})


def list_bundles(directory: str) -> list[dict]:
    """Bundle inventory for ``GET /debug/audit`` (name / reason / ts)."""
    out = []
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith("repro-") and not n.endswith(".part"))
    except OSError:
        return out
    for name in names:
        entry = {"name": name, "path": os.path.join(directory, name)}
        try:
            with open(os.path.join(directory, name, "manifest.json")) as fh:
                m = json.load(fh)
            entry.update(reason=m.get("reason"), ts=m.get("ts"),
                         job_id=m.get("job_id"), route=m.get("route"))
        except (OSError, ValueError):
            entry["reason"] = "unreadable manifest"
        out.append(entry)
    return out


# --- the serving daemon's background auditor ---


class ShadowAuditor(threading.Thread):
    """Low-priority shadow-oracle replay thread for the serving daemon.

    The dispatch worker offers completed jobs (with their already-decoded
    cubes) via :meth:`submit`; the queue is small and non-blocking — under
    load, audits are *sampled down* by back-pressure (``audit_skipped``
    counts the drops) instead of pinning cube-sized arrays or delaying
    the dispatch thread.  One replay at a time, pure numpy on host: the
    device never sees an audit.
    """

    def __init__(self, spool, repro_dir: str, on_divergence=None,
                 quiet: bool = False, queue_max: int = 8) -> None:
        super().__init__(daemon=True, name="ict-audit")
        self.spool = spool
        self.repro_dir = repro_dir
        self.on_divergence = on_divergence
        self.quiet = quiet
        self._q: queue.Queue = queue.Queue(maxsize=queue_max)
        self._recent: collections.deque = collections.deque(maxlen=20)
        # Accepted-but-unfinished count, incremented BEFORE the enqueue
        # and decremented only after the audit completes: drain() keys off
        # this, not queue emptiness, so the instant between a dequeue and
        # the audit starting can never read as "idle".
        self._outstanding = 0  # ict: guarded-by(self._lock)
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()

    def submit(self, job, D, w0, weights, scores, served_by: str,
               clean_cfg) -> bool:
        """Queue one completed job for auditing; False (and a counted
        skip) when the queue is full."""
        with self._lock:
            self._outstanding += 1
        try:
            self._q.put_nowait((job, np.asarray(D), np.asarray(w0),
                                np.asarray(weights), scores, served_by,
                                clean_cfg))
            return True
        except queue.Full:
            with self._lock:
                self._outstanding -= 1
            tracing.count("audit_skipped")
            return False

    def queue_depth(self) -> int:
        return self._q.qsize()

    def stop(self) -> None:
        """Non-blocking: a full audit queue must not stall the daemon's
        graceful stop behind a cube-sized oracle replay — queued audits
        are abandoned (the jobs already served their results)."""
        self._stop_evt.set()
        try:
            self._q.put_nowait(_STOP)
        except queue.Full:
            pass  # run() checks the event on every dequeued item

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until every accepted audit has finished (tests, the smoke
        check); True on success, False on timeout."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if self._outstanding == 0:
                    return True
            time.sleep(0.02)
        return False

    def recent(self) -> list[dict]:
        with self._lock:
            return list(self._recent)

    def run(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP or self._stop_evt.is_set():
                # Abandon whatever is still queued (stop() may not have
                # fit its sentinel into a full queue) and keep the
                # outstanding count honest on the way out.
                with self._lock:
                    if item is not _STOP:
                        self._outstanding -= 1
                    while True:
                        try:
                            nxt = self._q.get_nowait()
                        except queue.Empty:
                            break
                        if nxt is not _STOP:
                            self._outstanding -= 1
                return
            try:
                self._audit_one(*item)
            except Exception as exc:  # noqa: BLE001 — the thread must live
                tracing.count("audit_errors")
                if not self.quiet:
                    print(f"ict-serve: shadow audit failed: {exc}",
                          file=sys.stderr)
            finally:
                with self._lock:
                    self._outstanding -= 1

    def _audit_one(self, job, D, w0, weights, scores, served_by,
                   clean_cfg) -> None:
        with events.trace_scope(job.trace_id), tracing.phase("service_audit"):
            record, oracle_w = run_audit(
                D, w0, clean_cfg, weights, scores_served=scores,
                route=served_by)
        record["job_id"] = job.id
        bundle = None
        if not record["mask_identical"]:
            bundle = write_repro_bundle(
                self.repro_dir, D=D, w0=w0, cfg=clean_cfg,
                reason=f"shadow-audit divergence: job {job.id} "
                       f"(route {served_by})",
                weights_served=weights, weights_oracle=oracle_w,
                scores_served=scores, trace_id=job.trace_id,
                job_id=job.id, route=served_by, record=record)
            record["bundle"] = bundle
            if events.active():
                events.emit("audit_divergence", trace_id=job.trace_id,
                            job_id=job.id, route=served_by,
                            n_mask_diffs=record["n_mask_diffs"],
                            bundle=bundle or "")
            print(f"ict-serve: AUDIT DIVERGENCE job {job.id} "
                  f"(route {served_by}): {record['n_mask_diffs']} mask "
                  f"bit(s) differ from the numpy oracle"
                  + (f"; repro bundle at {bundle}" if bundle else ""),
                  file=sys.stderr)
        elif events.active():
            events.emit("audit_done", trace_id=job.trace_id, job_id=job.id,
                        route=served_by,
                        drift_within_bound=record["drift_within_bound"])
        with self._lock:
            self._recent.append(record)
        job.audit_result = record
        # Re-persist the manifest only once the worker's own terminal save
        # happened (the worker queues the audit just BEFORE that save): a
        # save here with state still "running" could win the race and
        # leave a served job looking unfinished to a restart replay.  The
        # worker's transition is microseconds away, so the wait is
        # bounded-short and normally zero iterations.
        from iterative_cleaner_tpu.service.jobs import TERMINAL

        deadline = time.time() + 5.0
        while job.state not in TERMINAL and time.time() < deadline:
            time.sleep(0.005)
        if job.state in TERMINAL:
            try:
                self.spool.save(job)
            except Exception:  # noqa: BLE001 — the job already served
                pass
        # Escalation keys off the CONFIRMED divergence, never off the
        # bundle write succeeding: a full spool disk (likely exactly when
        # a route diverges repeatedly — each bundle holds a cube) must not
        # keep a wrong-mask route in service.
        if not record["mask_identical"] and self.on_divergence is not None:
            self.on_divergence(record)
