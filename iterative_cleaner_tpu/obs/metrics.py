"""Prometheus text exposition over the :mod:`.tracing` registries.

The serving daemon's ``/metrics`` renders this (the legacy raw-JSON
snapshot moved to ``/metrics.json``).  Three metric classes:

- **flat counters/gauges** — every registry entry verbatim under an
  ``ict_`` prefix, so the established internal names stay the operator
  vocabulary: ``ict_service_load_s`` (total seconds, counter),
  ``ict_service_load_n`` (count, counter), ``ict_service_load_err_n``
  (failures, counter), ``ict_service_load_max_s`` (worst single
  occurrence, gauge), plus the plain event counters
  (``ict_service_jobs_done`` …).  Every ``_s`` total has a matching
  ``_n`` count by construction (observe_phase writes both under one
  lock) — pinned by tests/test_observability.py.
- **histograms** — one family ``ict_phase_duration_seconds`` labeled by
  ``phase``, cumulative log2 buckets (``le`` bounds from
  tracing.HIST_BOUNDS) with ``_sum``/``_count`` taken from the same
  ``_s``/``_n`` counters.
- **labeled counters** — ``ict_<family>{label="..."}`` from
  tracing.count_labeled (compiles / compile seconds per ``shape_bucket``,
  jobs per ``route``, …).
- **gauges** — flat (``ict_host_rss_bytes``) and labeled
  (``ict_hbm_bytes_in_use{device=...}``,
  ``ict_route_hbm_peak_bytes{route=...}``,
  ``ict_executable_bytes_accessed{shape_bucket=...}``) from
  tracing.set_gauge / set_gauge_labeled / max_gauge_labeled — the
  memory/cost accounting of obs/memory.py.
"""

from __future__ import annotations

from iterative_cleaner_tpu.obs import tracing

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(value: float) -> str:
    """Prometheus sample value: integral floats render as ints (bucket
    counts must not read as '3.0' in a strict parser)."""
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(pairs) -> str:
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}" if inner else ""


def render_prometheus() -> str:
    """One consistent scrape of every registry, Prometheus text format."""
    counters, labeled, gauges, labeled_gauges, hists = (
        tracing.registry_snapshot())
    lines: list[str] = []

    # --- phase latency histograms (cumulative buckets, label: phase) ---
    if hists:
        lines.append("# HELP ict_phase_duration_seconds per-phase latency, "
                     "fixed log2 buckets")
        lines.append("# TYPE ict_phase_duration_seconds histogram")
        for phase, buckets in hists.items():
            cum = 0
            for bound, n in zip(tracing.HIST_BOUNDS, buckets):
                cum += n
                lines.append(
                    "ict_phase_duration_seconds_bucket"
                    + _labels([("phase", phase), ("le", repr(bound))])
                    + f" {cum}")
            cum += buckets[-1]
            lines.append(
                "ict_phase_duration_seconds_bucket"
                + _labels([("phase", phase), ("le", "+Inf")]) + f" {cum}")
            lines.append(
                "ict_phase_duration_seconds_sum"
                + _labels([("phase", phase)])
                + f" {_fmt(counters.get(f'{phase}_s', 0.0))}")
            lines.append(
                "ict_phase_duration_seconds_count"
                + _labels([("phase", phase)])
                + f" {_fmt(counters.get(f'{phase}_n', 0.0))}")

    # --- flat counters / gauges, internal names preserved ---
    for name, value in counters.items():
        kind = "gauge" if name.endswith("_max_s") else "counter"
        lines.append(f"# TYPE ict_{name} {kind}")
        lines.append(f"ict_{name} {_fmt(value)}")

    # --- flat gauges (set_gauge: point-in-time facts like host RSS) ---
    for name, value in gauges.items():
        lines.append(f"# TYPE ict_{name} gauge")
        lines.append(f"ict_{name} {_fmt(value)}")

    # --- labeled counters (grouped per family for one TYPE line) ---
    seen_families: set[str] = set()
    for (family, label_pairs), value in labeled.items():
        if family not in seen_families:
            seen_families.add(family)
            lines.append(f"# TYPE ict_{family} counter")
        lines.append(f"ict_{family}{_labels(label_pairs)} {_fmt(value)}")

    # --- labeled gauges (device / route / shape_bucket memory views) ---
    seen_families.clear()
    for (family, label_pairs), value in labeled_gauges.items():
        if family not in seen_families:
            seen_families.add(family)
            lines.append(f"# TYPE ict_{family} gauge")
        lines.append(f"ict_{family}{_labels(label_pairs)} {_fmt(value)}")

    return "\n".join(lines) + "\n"
