"""Prometheus text exposition over the :mod:`.tracing` registries.

The serving daemon's ``/metrics`` renders this (the legacy raw-JSON
snapshot moved to ``/metrics.json``).  Three metric classes:

- **flat counters/gauges** — every registry entry verbatim under an
  ``ict_`` prefix, so the established internal names stay the operator
  vocabulary: ``ict_service_load_s`` (total seconds, counter),
  ``ict_service_load_n`` (count, counter), ``ict_service_load_err_n``
  (failures, counter), ``ict_service_load_max_s`` (worst single
  occurrence, gauge), plus the plain event counters
  (``ict_service_jobs_done`` …).  Every ``_s`` total has a matching
  ``_n`` count by construction (observe_phase writes both under one
  lock) — pinned by tests/test_observability.py.
- **histograms** — one family ``ict_phase_duration_seconds`` labeled by
  ``phase``, cumulative log2 buckets (``le`` bounds from
  tracing.HIST_BOUNDS) with ``_sum``/``_count`` taken from the same
  ``_s``/``_n`` counters.
- **labeled counters** — ``ict_<family>{label="..."}`` from
  tracing.count_labeled (compiles / compile seconds per ``shape_bucket``,
  jobs per ``route``, …).
- **gauges** — flat (``ict_host_rss_bytes``) and labeled
  (``ict_hbm_bytes_in_use{device=...}``,
  ``ict_route_hbm_peak_bytes{route=...}``,
  ``ict_executable_bytes_accessed{shape_bucket=...}``) from
  tracing.set_gauge / set_gauge_labeled / max_gauge_labeled — the
  memory/cost accounting of obs/memory.py.

This module also owns the *strict text-format parser* for the same
exposition (:func:`parse_exposition` / :class:`MetricFamily` /
:func:`render_exposition`): the fleet router's metrics federation
(fleet/obs.py) parses every replica scrape with it, and the round-trip is
exact — ``render_exposition(parse_exposition(text)) == text`` for
anything this module (or the router's registry renderer) produced — so
the parser, the renderer, and the grammar tests can never drift apart.
:func:`render_registries` is the one shared renderer for plain
``{(family, label_pairs) -> value}`` counter/gauge registries (the fleet
router's ``RouterMetrics.render`` delegates here).
"""

from __future__ import annotations

import dataclasses
import re

from iterative_cleaner_tpu.obs import tracing

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(value: float) -> str:
    """Prometheus sample value: integral floats render as ints (bucket
    counts must not read as '3.0' in a strict parser), and the IEEE
    specials render as the exposition's ``+Inf``/``-Inf``/``NaN``
    spellings (repr's ``inf`` would fail the strict sample grammar —
    the capacity model's backlog-drain ETA is legitimately ``+Inf``
    while backlog exists with a zero observed service rate)."""
    value = float(value)
    if value != value:
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if value.is_integer():
        return str(int(value))
    return repr(value)


def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(pairs) -> str:
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}" if inner else ""


def render_prometheus() -> str:
    """One consistent scrape of every registry, Prometheus text format."""
    counters, labeled, gauges, labeled_gauges, hists = (
        tracing.registry_snapshot())
    lines: list[str] = []

    # --- phase latency histograms (cumulative buckets, label: phase) ---
    if hists:
        lines.append("# HELP ict_phase_duration_seconds per-phase latency, "
                     "fixed log2 buckets")
        lines.append("# TYPE ict_phase_duration_seconds histogram")
        for phase, buckets in hists.items():
            cum = 0
            for bound, n in zip(tracing.HIST_BOUNDS, buckets):
                cum += n
                lines.append(
                    "ict_phase_duration_seconds_bucket"
                    + _labels([("phase", phase), ("le", repr(bound))])
                    + f" {cum}")
            cum += buckets[-1]
            lines.append(
                "ict_phase_duration_seconds_bucket"
                + _labels([("phase", phase), ("le", "+Inf")]) + f" {cum}")
            lines.append(
                "ict_phase_duration_seconds_sum"
                + _labels([("phase", phase)])
                + f" {_fmt(counters.get(f'{phase}_s', 0.0))}")
            lines.append(
                "ict_phase_duration_seconds_count"
                + _labels([("phase", phase)])
                + f" {_fmt(counters.get(f'{phase}_n', 0.0))}")

    # --- flat counters / gauges, internal names preserved ---
    for name, value in counters.items():
        kind = "gauge" if name.endswith("_max_s") else "counter"
        lines.append(f"# TYPE ict_{name} {kind}")
        lines.append(f"ict_{name} {_fmt(value)}")

    # --- flat gauges (set_gauge: point-in-time facts like host RSS) ---
    for name, value in gauges.items():
        lines.append(f"# TYPE ict_{name} gauge")
        lines.append(f"ict_{name} {_fmt(value)}")

    # --- labeled counters (grouped per family for one TYPE line) ---
    seen_families: set[str] = set()
    for (family, label_pairs), value in labeled.items():
        if family not in seen_families:
            seen_families.add(family)
            lines.append(f"# TYPE ict_{family} counter")
        lines.append(f"ict_{family}{_labels(label_pairs)} {_fmt(value)}")

    # --- labeled gauges (device / route / shape_bucket memory views) ---
    seen_families.clear()
    for (family, label_pairs), value in labeled_gauges.items():
        if family not in seen_families:
            seen_families.add(family)
            lines.append(f"# TYPE ict_{family} gauge")
        lines.append(f"ict_{family}{_labels(label_pairs)} {_fmt(value)}")

    return "\n".join(lines) + "\n"


def render_registries(counters: dict, gauges: dict,
                      prefix: str = "ict_", hists: dict | None = None,
                      ) -> str:
    """Render plain ``{(family, ((label, value), ...)) -> float}`` counter
    and gauge registries as Prometheus text — the ONE implementation of
    the flat-registry exposition, shared by the fleet router's
    ``RouterMetrics`` (its registry is deliberately separate from the
    process-global one, but its *grammar* must not be a second
    implementation).

    ``hists`` is the optional histogram table:
    ``{(family, label_pairs) -> (bounds, per-bucket counts, sum)}`` with
    ``len(counts) == len(bounds) + 1`` (the trailing slot is the +Inf
    overflow).  Rendered as proper cumulative ``_bucket``/``_sum``/
    ``_count`` samples (the render_prometheus phase-histogram grammar),
    so :func:`bucket_cum` / :func:`quantile_from_cum` read them back —
    the fleet SLO plane's per-journey latency quantiles ride this."""
    lines: list[str] = []
    for kind, table in (("counter", counters), ("gauge", gauges)):
        seen: set[str] = set()
        for (family, label_pairs) in sorted(table):
            if family not in seen:
                seen.add(family)
                lines.append(f"# TYPE {prefix}{family} {kind}")
            lines.append(f"{prefix}{family}{_labels(label_pairs)} "
                         f"{_fmt(table[(family, label_pairs)])}")
    seen_h: set[str] = set()
    for (family, label_pairs) in sorted(hists or {}):
        bounds, buckets, total_sum = hists[(family, label_pairs)]
        if family not in seen_h:
            seen_h.add(family)
            lines.append(f"# TYPE {prefix}{family} histogram")
        cum = 0.0
        for bound, n in zip(bounds, buckets):
            cum += n
            lines.append(f"{prefix}{family}_bucket"
                         + _labels(tuple(label_pairs)
                                   + (("le", repr(float(bound))),))
                         + f" {_fmt(cum)}")
        cum += buckets[-1]
        lines.append(f"{prefix}{family}_bucket"
                     + _labels(tuple(label_pairs) + (("le", "+Inf"),))
                     + f" {_fmt(cum)}")
        lines.append(f"{prefix}{family}_sum{_labels(label_pairs)} "
                     f"{_fmt(total_sum)}")
        lines.append(f"{prefix}{family}_count{_labels(label_pairs)} "
                     f"{_fmt(cum)}")
    # Empty registries render as the empty exposition, not a lone "\n" —
    # a freshly started router's first scrape must still parse strictly.
    return "\n".join(lines) + "\n" if lines else ""


# --- the strict text-format parser (the federation's inbound half) ---

#: Metric/sample name and label-key grammars (the Prometheus data model);
#: values are the exposition's number grammar plus the +/-Inf / NaN
#: specials the renderer can emit via ``repr(float)``.
_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_NAME_RE}) (.+)$")
_TYPE_RE = re.compile(
    rf"^# TYPE ({_NAME_RE}) (counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(
    rf"^({_NAME_RE})(?:\{{(.*)\}})? "
    r"(-?[0-9]+(?:\.[0-9]+)?(?:[eE][+-]?[0-9]+)?|[+-]?Inf|NaN)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

#: Histogram sample-name suffixes (`<family>_bucket` / `_sum` / `_count`).
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


@dataclasses.dataclass
class MetricFamily:
    """One parsed exposition family: the ``# TYPE`` header (``kind`` is
    None for samples that appeared without one), the optional ``# HELP``
    text, and the samples in file order — each ``(sample_name,
    label_pairs, raw_value)`` with the value kept as the exact source
    string so re-rendering round-trips byte-for-byte."""

    name: str
    kind: str | None = None
    help: str | None = None
    samples: list = dataclasses.field(default_factory=list)


def _unescape(value: str) -> str:
    """Inverse of :func:`_escape` (label-value backslash escapes)."""
    out: list[str] = []
    i = 0
    while i < len(value):
        if value[i] == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt in ("\\", '"'):
                out.append(nxt)
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(value[i])
        i += 1
    return "".join(out)


def _parse_label_pairs(raw: str) -> tuple:
    """Parse the inside of ``{...}`` strictly; raises ValueError on any
    residue the label grammar does not cover."""
    pairs: list[tuple[str, str]] = []
    pos = 0
    while pos < len(raw):
        m = _LABEL_RE.match(raw, pos)
        if m is None:
            raise ValueError(f"bad label syntax at {raw[pos:]!r}")
        pairs.append((m.group(1), _unescape(m.group(2))))
        pos = m.end()
        if pos < len(raw):
            if raw[pos] != ",":
                raise ValueError(f"bad label separator at {raw[pos:]!r}")
            pos += 1
    return tuple(pairs)


def _sample_family(name: str, current: MetricFamily | None) -> bool:
    """Whether a sample named ``name`` belongs to ``current`` (exact name,
    or a histogram-suffixed one for histogram families)."""
    if current is None:
        return False
    if name == current.name:
        return True
    return (current.kind == "histogram"
            and any(name == current.name + sfx for sfx in _HIST_SUFFIXES))


def parse_exposition(text: str) -> list[MetricFamily]:
    """Parse Prometheus text exposition strictly into families.

    Raises ValueError on any line outside the grammar — the parse IS the
    grammar check the fleet smoke and the federation tests rely on.
    Samples with no preceding ``# TYPE`` become kind-None families (the
    renderer then emits no TYPE line, preserving the round-trip)."""
    families: list[MetricFamily] = []
    pending_help: tuple[str, str] | None = None
    current: MetricFamily | None = None
    for line in text.splitlines():
        if not line:
            continue   # the format permits blank lines; none are emitted
        m = _HELP_RE.match(line)
        if m is not None:
            pending_help = (m.group(1), m.group(2))
            continue
        m = _TYPE_RE.match(line)
        if m is not None:
            current = MetricFamily(name=m.group(1), kind=m.group(2))
            if pending_help is not None and pending_help[0] == current.name:
                current.help = pending_help[1]
            pending_help = None
            families.append(current)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"bad exposition line: {line!r}")
        name, raw_labels, raw_value = m.group(1), m.group(2), m.group(3)
        labels = _parse_label_pairs(raw_labels) if raw_labels else ()
        if not _sample_family(name, current):
            current = MetricFamily(name=name, kind=None)
            families.append(current)
        current.samples.append((name, labels, raw_value))
    return families


def render_exposition(families: list[MetricFamily]) -> str:
    """Inverse of :func:`parse_exposition`: HELP line (when recorded),
    TYPE line (when typed), samples with raw values verbatim."""
    lines: list[str] = []
    for fam in families:
        if fam.help is not None:
            lines.append(f"# HELP {fam.name} {fam.help}")
        if fam.kind is not None:
            lines.append(f"# TYPE {fam.name} {fam.kind}")
        for name, labels, raw_value in fam.samples:
            lines.append(f"{name}{_labels(labels)} {raw_value}")
    return "\n".join(lines) + "\n" if lines else ""


def sample_value(raw: str) -> float:
    """Numeric value of a raw sample string (``+Inf``/``NaN`` included)."""
    if raw in ("+Inf", "Inf"):
        return float("inf")
    if raw == "-Inf":
        return float("-inf")
    return float(raw)


# --- shared histogram-bucket math (the one quantile estimator) ---
#
# The straggler detector (fleet/obs.py), the capacity model
# (fleet/capacity.py), and the alert engine's rate/quantile predicates
# (fleet/alerts.py) all estimate quantiles off the same fixed-bound
# cumulative bucket counts.  One estimator, one set of edge-case tests
# (tests/test_fleet_alerts.py) — a drifted second implementation would
# make two layers disagree about the same scrape.


def bucket_cum(families: list[MetricFamily], family: str,
               labels: dict[str, str] | None = None) -> dict[float, float]:
    """Cumulative bucket counts (``le`` bound -> count) for one histogram
    family out of a parsed scrape, filtered to samples whose label pairs
    contain every ``labels`` entry; empty when nothing matches.

    A grammar-valid scrape may still carry a foreign (non-numeric) ``le``
    bound — skipped, never raised, so the poll/alert threads that call
    this survive any replica's exposition."""
    want = dict(labels or {})
    out: dict[float, float] = {}
    for fam in families:
        if fam.name != family:
            continue
        for name, label_pairs, raw in fam.samples:
            if not name.endswith("_bucket"):
                continue
            d = dict(label_pairs)
            if any(d.get(k) != v for k, v in want.items()):
                continue
            try:
                out[sample_value(d.get("le", "+Inf"))] = sample_value(raw)
            except ValueError:
                continue
    return out


def quantile_from_cum(cum: dict[float, float], q: float) -> float | None:
    """Upper-bound quantile estimate from cumulative bucket counts: the
    smallest ``le`` whose cumulative count reaches ``q`` of the total.
    None when the histogram is empty or its total is non-positive."""
    if not cum:
        return None
    bounds = sorted(cum)
    total = cum[bounds[-1]]
    if total <= 0:
        return None
    target = q * total
    for bound in bounds:
        if cum[bound] >= target:
            return bound
    return bounds[-1]
