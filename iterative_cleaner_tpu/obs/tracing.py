"""Process-global metrics registry + profiling hooks.

Grown from ``utils/tracing.py`` (PR 3): the monotonic counter dict that the
serving daemon's ``/metrics.json`` reports is still here, unchanged in
shape, but every :func:`observe_phase` now also lands in a fixed
log2-bucket latency histogram (rendered in Prometheus text form by
:mod:`.metrics` — the max-only tail gauge was the cheapest tail statistic,
a histogram is the honest one), failures get their own ``<name>_err_n``
counter, and a small labeled-counter registry carries the dimensions flat
names cannot (route, shape bucket).  ``observe_phase`` keeps the Prometheus
summary convention (``<name>_s`` total seconds + ``<name>_n`` count), which
is what the per-stage accounting of astronomical pipelines needs
("Pipeline Collector", arXiv:1807.05733): mean stage latency is
``load_s / load_n``.

Everything is process-global on purpose: every layer (driver, batch
dispatch, service worker, online session) accounts into one place without
plumbing a registry object through call signatures.
"""

from __future__ import annotations

import contextlib
import threading
import time

from iterative_cleaner_tpu.obs import flight

# The one-shot profiler context grew into obs/profiling.py (the daemon's
# bounded-capture facility); re-exported here for its historical import
# sites (driver.py, utils/tracing shim).
from iterative_cleaner_tpu.obs.profiling import profile_trace  # noqa: F401


# --- the registries (one lock: a /metrics scrape sees a consistent cut) ---

#: Fixed log2 histogram bucket upper bounds (seconds): 16 finite bounds,
#: 2^-10 (~0.98 ms) through 2^5 (32 s), plus the implicit +Inf bucket.
#: Fixed, not adaptive: every phase shares one bucket layout so cross-phase
#: comparison and the Prometheus exposition stay trivial, and bucketing is
#: a 16-entry linear scan — no histogram state to size.
HIST_BOUNDS: tuple[float, ...] = tuple(2.0 ** e for e in range(-10, 6))

_counters: dict[str, float] = {}  # ict: guarded-by(_counters_lock)
_labeled: dict[tuple[str, tuple[tuple[str, str], ...]], float] = {}  # ict: guarded-by(_counters_lock)
_gauges: dict[str, float] = {}  # ict: guarded-by(_counters_lock)
_labeled_gauges: dict[tuple[str, tuple[tuple[str, str], ...]], float] = {}  # ict: guarded-by(_counters_lock)
_hists: dict[str, list[int]] = {}  # ict: guarded-by(_counters_lock)
_counters_lock = threading.Lock()


def _bucket_index(seconds: float) -> int:
    """Index of the first bound >= seconds (len(HIST_BOUNDS) = the +Inf
    bucket); a linear scan over the 16 finite bounds."""
    for i, bound in enumerate(HIST_BOUNDS):
        if seconds <= bound:
            return i
    return len(HIST_BOUNDS)


def count(name: str, inc: float = 1.0) -> None:
    """Add ``inc`` to the process-global counter ``name``."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0.0) + inc


def count_labeled(family: str, labels: dict[str, str], inc: float = 1.0) -> None:
    """Add ``inc`` to the labeled counter ``family{labels}`` — the register
    for dimensions a flat name cannot carry (route, shape bucket).  Label
    sets are expected to stay low-cardinality (shape classes, route names);
    the registry is a plain dict, so an unbounded label value would grow it
    without bound."""
    key = (family, tuple(sorted((str(k), str(v)) for k, v in labels.items())))
    with _counters_lock:
        _labeled[key] = _labeled.get(key, 0.0) + inc


def set_gauge(name: str, value: float) -> None:
    """Set the absolute value of the gauge ``name`` (last write wins — the
    register for point-in-time facts like host RSS, where a counter's
    only-up contract would lie)."""
    with _counters_lock:
        _gauges[name] = float(value)


def set_gauge_labeled(family: str, labels: dict[str, str],
                      value: float) -> None:
    """Labeled gauge (device / route / shape_bucket dimensions), absolute
    value, last write wins.  Same low-cardinality expectation as
    :func:`count_labeled`."""
    key = (family, tuple(sorted((str(k), str(v)) for k, v in labels.items())))
    with _counters_lock:
        _labeled_gauges[key] = float(value)


def max_gauge_labeled(family: str, labels: dict[str, str],
                      value: float) -> None:
    """Labeled gauge that only ratchets upward — high-water marks
    (per-route peak HBM) where a later, lower sample must not erase the
    peak the operator is alerting on."""
    key = (family, tuple(sorted((str(k), str(v)) for k, v in labels.items())))
    with _counters_lock:
        if float(value) > _labeled_gauges.get(key, float("-inf")):
            _labeled_gauges[key] = float(value)


def observe_phase(name: str, seconds: float, error: bool = False) -> None:
    """Record one completed phase: total seconds + occurrence count + the
    worst single occurrence (``<name>_max_s``) + one log2 histogram bucket.
    ``error=True`` additionally bumps ``<name>_err_n`` — failed occurrences
    still count in ``_n``/``_s`` (a failing load is still a load the
    operator wants in the latency accounting) but become visible as a
    failure *rate* on ``/metrics``."""
    with _counters_lock:
        _counters[f"{name}_s"] = _counters.get(f"{name}_s", 0.0) + seconds
        _counters[f"{name}_n"] = _counters.get(f"{name}_n", 0.0) + 1.0
        if error:
            _counters[f"{name}_err_n"] = _counters.get(f"{name}_err_n", 0.0) + 1.0
        key = f"{name}_max_s"
        if seconds > _counters.get(key, 0.0):
            _counters[key] = seconds
        hist = _hists.get(name)
        if hist is None:
            hist = _hists[name] = [0] * (len(HIST_BOUNDS) + 1)
        hist[_bucket_index(seconds)] += 1
    # Outside the lock: the flight recorder (obs/flight) keeps its own —
    # phase timings are the "what was it doing" half of a post-mortem ring.
    flight.note_phase(name, seconds, error=error)


@contextlib.contextmanager
def phase(name: str):
    """Time a block into :func:`observe_phase`.  Exceptions still count in
    the totals (see observe_phase) AND bump ``<name>_err_n``, so failure
    rates are first-class on ``/metrics`` instead of masquerading as
    successes."""
    t0 = time.perf_counter()
    try:
        yield
    except BaseException:
        observe_phase(name, time.perf_counter() - t0, error=True)
        raise
    else:
        observe_phase(name, time.perf_counter() - t0)


def counters_snapshot() -> dict[str, float]:
    """Point-in-time copy of every flat counter, sorted by name (stable
    JSON — the ``/metrics.json`` payload)."""
    with _counters_lock:
        return dict(sorted(_counters.items()))


def labeled_snapshot() -> dict[tuple[str, tuple[tuple[str, str], ...]], float]:
    """Point-in-time copy of the labeled-counter registry."""
    with _counters_lock:
        return dict(sorted(_labeled.items()))


def histograms_snapshot() -> dict[str, list[int]]:
    """Point-in-time copy of every phase histogram (per-bucket counts, NOT
    cumulative; the Prometheus renderer accumulates)."""
    with _counters_lock:
        return {k: list(v) for k, v in sorted(_hists.items())}


def registry_snapshot() -> tuple[dict, dict, dict, dict, dict]:
    """(counters, labeled, gauges, labeled_gauges, histograms) under ONE
    lock hold — the scrape path's view, so a histogram's +Inf bucket can
    never disagree with its ``_n`` counter mid-observation."""
    with _counters_lock:
        return (
            dict(sorted(_counters.items())),
            dict(sorted(_labeled.items())),
            dict(sorted(_gauges.items())),
            dict(sorted(_labeled_gauges.items())),
            {k: list(v) for k, v in sorted(_hists.items())},
        )


def gauges_snapshot() -> tuple[dict, dict]:
    """Point-in-time copy of the flat and labeled gauge registries."""
    with _counters_lock:
        return dict(sorted(_gauges.items())), dict(sorted(_labeled_gauges.items()))


def snapshot(prefix: str = "") -> dict[str, float]:
    """:func:`counters_snapshot`, optionally filtered to one subsystem's
    ``prefix`` — the before/after idiom tests use so counter state from one
    case never bleeds into another's assertions (delta = snapshot() minus an
    earlier snapshot(), no global reset needed mid-process)."""
    snap = counters_snapshot()
    if not prefix:
        return snap
    return {k: v for k, v in snap.items() if k.startswith(prefix)}


def delta(before: dict[str, float], key: str) -> float:
    """Counter movement since a :func:`snapshot`; missing keys read 0."""
    return counters_snapshot().get(key, 0.0) - before.get(key, 0.0)


def reset_counters() -> None:
    """Zero every registry (tests only — production counters are cumulative
    for the life of the process, like any scrape target)."""
    with _counters_lock:
        _counters.clear()
        _labeled.clear()
        _gauges.clear()
        _labeled_gauges.clear()
        _hists.clear()


# --- compile accounting (utils/compile_cache.py + the jax monitoring bus) ---

_tls = threading.local()
# Set-once latch, written only from single-threaded process setup (CLI
# main / daemon _start_locked / bench init before any worker exists).
_listener_installed = False  # ict: guarded-by(none: set once during single-threaded startup)


def shape_bucket_label(shape) -> str:
    """Canonical shape-bucket label: '8x16x64' (leading int dims only)."""
    return "x".join(str(int(v)) for v in shape)


@contextlib.contextmanager
def compile_scope(shape_bucket: str):
    """Attribute any jax backend compile that fires inside this block to
    ``shape_bucket`` (thread-local: jit compiles run synchronously on the
    calling thread, so the monitoring callback fires in-scope)."""
    prev = getattr(_tls, "shape_bucket", "")
    _tls.shape_bucket = shape_bucket
    try:
        yield
    finally:
        _tls.shape_bucket = prev


def install_compile_listener() -> bool:
    """Register a jax.monitoring listener that accounts real backend
    compiles (count + seconds, per shape bucket when a
    :func:`compile_scope` is active) and persistent-compilation-cache
    events into this registry.  Idempotent; best-effort — a drifted private
    monitoring surface just means compiles stay unaccounted.  Only call on
    the JAX path (it imports jax)."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax._src import monitoring
    except Exception:  # noqa: BLE001 — private-API drift tolerated
        return False

    def _on_duration(name, dur, **kw):
        if name.endswith("backend_compile_duration"):
            observe_phase("jax_compile", dur)
            bucket = getattr(_tls, "shape_bucket", "") or "unscoped"
            count_labeled("compiles_total", {"shape_bucket": bucket})
            count_labeled("compile_seconds_total", {"shape_bucket": bucket},
                          dur)

    def _on_event(name, **kw):
        # e.g. '/jax/compilation_cache/cache_hits' — the persistent on-disk
        # cache's own accounting, surfaced next to ours.
        if "/compilation_cache/" in name:
            count(f"persistent_{name.rsplit('/', 1)[-1]}")

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
    except Exception:  # noqa: BLE001 — accounting is opportunistic
        return False
    _listener_installed = True
    return True


class StepTimer:
    """Wall-clock per iteration, reported through the progress callback.
    perf_counter: monotonic (no negative laps on wall-clock steps) and
    high-resolution (no 0.0 laps on coarse system clocks)."""

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self.durations: list[float] = []

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        self.durations.append(dt)
        return dt
