from iterative_cleaner_tpu.core.cleaner import CleanResult, clean_cube, find_bad_parts

__all__ = ["CleanResult", "clean_cube", "find_bad_parts"]
