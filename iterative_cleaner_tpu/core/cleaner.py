"""Backend-agnostic iterative cleaning loop.

Reproduces the reference's ``clean()`` iteration dynamics and convergence
bookkeeping (iterative_cleaner.py:64-145; SURVEY.md §3.2):

- weights feed back *only through the template*: each step's stats are
  computed against the frozen original weights (§8.L11), while ``w_prev``
  (the previous iteration's zaps) shapes the template;
- convergence is full-history cycle detection, with the pre-loop weights in
  the history (§8.L10), so oscillating masks also terminate;
- ``loops`` records the stopping iteration (it names the residual archive and
  appears in the log).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.backends.base import make_backend
from iterative_cleaner_tpu.utils.compile_cache import (
    inmemory_route_key,
    note_compiled_shape,
)


@dataclass
class IterationInfo:
    index: int                 # 1-based loop counter (reference's `x`)
    diff_weights: int          # mask churn: entries changed vs previous
                               # weights (XOR popcount of the binarised masks)
    rfi_frac: float            # zapped fraction after this iteration
    duration_s: float = 0.0    # host wall-clock of this iteration's step
    n_new_zaps: int = 0        # profiles newly zapped this iteration
    n_unzapped: int = 0        # profiles restored this iteration
    # Per-diagnostic vote counts among this iteration's zaps (std/mean/ptp/
    # fft) — filled only under ICT_FORENSICS=1 (obs/forensics.py: a host
    # replay of the oracle score pipeline; expensive, so asked-for).
    zaps_by_diagnostic: dict | None = None


@dataclass
class CleanResult:
    weights: np.ndarray        # final (nsub, nchan) weights (before bad-parts sweep)
    test_results: np.ndarray   # last iteration's outlier scores
    loops: int                 # stopping iteration (reference's `loops`)
    converged: bool            # True if the mask reached a fixed point / cycle
    iterations: list[IterationInfo] = field(default_factory=list)
    history: list[np.ndarray] = field(default_factory=list)
    residual: np.ndarray | None = None   # unweighted amp*t − D, dedispersed frame
    timed: bool = False                  # iterations carry real host wall-clock
                                         # laps (stepwise loops; the fused
                                         # single dispatch has none)
    termination: str = ""                # "fixed_point" | "cycle" | "max_iter"
                                         # ("" on routes that track no history,
                                         # e.g. the sharded auto-reroute)

    @property
    def rfi_frac(self) -> float:
        if self.iterations:
            return self.iterations[-1].rfi_frac
        # The sharded reroute tracks no per-iteration info; derive from the
        # final weights (identical to the stepwise final-iteration value:
        # zapped entries are exactly 0.0).
        return float((self.weights == 0).mean())

    def quality_summary(self) -> dict:
        """RFI data-quality facts of this clean's mask (obs/quality.py):
        zap fraction, per-channel/per-subint occupancy histograms,
        fully-zapped counts, termination reason.  Pre-sweep weights — the
        daemon computes the same summary on the final served mask."""
        from iterative_cleaner_tpu.obs import quality

        return quality.quality_summary(self.weights,
                                       termination=self.termination)


ProgressFn = Callable[[IterationInfo], None]


@dataclass
class LoopState:
    """Resumable state of the canonical convergence loop.

    Everything the stepwise iteration carries between ``backend.step`` calls
    — the weight history (cycle detection, §8.L10), per-loop records, and
    the stopping bookkeeping — extracted so the batch path (clean_cube) and
    the online streaming passes (online/session.py) share ONE loop
    implementation instead of two that could drift.  ``start(w)`` seeds the
    pre-loop weights into the history exactly as the reference seeds
    ``test_weights`` (iterative_cleaner.py:77-78); the online pass seeds the
    previous provisional mask instead of w0, which only shapes the first
    template (stats always run against the backend's frozen w0, §8.L11).
    """

    w_prev: np.ndarray
    history: list[np.ndarray]
    infos: list[IterationInfo] = field(default_factory=list)
    test_results: np.ndarray | None = None
    loops: int = 0
    converged: bool = False
    termination: str = ""      # forensics: "fixed_point" | "cycle" | "max_iter"

    @classmethod
    def start(cls, w_init: np.ndarray) -> "LoopState":
        w = np.asarray(w_init, dtype=np.float32)
        return cls(w_prev=w, history=[w.copy()])

    def advance(self, backend, progress: ProgressFn | None = None,
                timer=None) -> bool:
        """Run one iteration; returns True when the loop should stop
        (the new mask reproduced any mask in the history)."""
        from iterative_cleaner_tpu.obs import events, forensics

        x = len(self.infos) + 1
        test_results, new_w = backend.step(self.w_prev)
        self.test_results = np.asarray(test_results)
        new_w = np.asarray(new_w)

        info = _iteration_info(x, self.history[-1], new_w,
                               duration_s=timer.lap() if timer else 0.0)
        if forensics.attribution_enabled():
            # Read-only host replay of the oracle score pipeline — which
            # diagnostic voted for each of this iteration's zaps.  Uses the
            # TEMPLATE weights (self.w_prev), the inputs the step ran with.
            info.zaps_by_diagnostic = forensics.attribute_from_backend(
                backend, self.w_prev, new_w)
        self.infos.append(info)
        if progress is not None:
            progress(info)
        if events.active():
            events.emit("iteration", **forensics.iteration_record(info))

        # Full-history cycle detection, pre-loop weights included (§8.L10);
        # a match against the immediately previous mask is a fixed point,
        # anything older a genuine oscillation.
        fixed = np.array_equal(new_w, self.history[-1])
        stop = fixed or any(
            np.array_equal(new_w, old) for old in self.history[:-1])
        self.history.append(new_w)
        self.w_prev = new_w
        if stop:
            self.loops = x
            self.converged = True
            self.termination = "fixed_point" if fixed else "cycle"
        return stop

    def run(self, backend, max_iter: int,
            progress: ProgressFn | None = None, timed: bool = True) -> None:
        """Advance until convergence or ``max_iter`` TOTAL iterations (a
        resumed state counts the iterations it already ran)."""
        from iterative_cleaner_tpu.obs.tracing import StepTimer

        timer = StepTimer() if timed else None
        while len(self.infos) < max_iter:
            if self.advance(backend, progress=progress, timer=timer):
                break
        if not self.converged:
            self.loops = max_iter
            self.termination = "max_iter"

    def result(self, residual: np.ndarray | None = None,
               timed: bool = False) -> CleanResult:
        return CleanResult(
            weights=self.history[-1].copy(),
            test_results=self.test_results,
            loops=self.loops,
            converged=self.converged,
            iterations=self.infos,
            history=self.history,
            residual=residual,
            timed=timed,
            termination=self.termination,
        )


def _iteration_info(
    index: int, prev_w: np.ndarray, new_w: np.ndarray, duration_s: float = 0.0
) -> IterationInfo:
    """The per-loop record the reference prints (diff vs previous weights,
    zapped fraction — iterative_cleaner.py:127-133); shared by the stepwise
    loop and the fused path's post-hoc derivation so the two can never
    diverge.  The churn split (newly zapped vs restored) is the forensics
    view of the same XOR: both are O(nsub*nchan) host ops on the mask."""
    return IterationInfo(
        index=index,
        diff_weights=int(np.sum(new_w != prev_w)),
        rfi_frac=float((new_w.size - np.count_nonzero(new_w)) / new_w.size),
        duration_s=duration_s,
        n_new_zaps=int(np.sum((new_w == 0) & (prev_w != 0))),
        n_unzapped=int(np.sum((new_w != 0) & (prev_w == 0))),
    )


def clean_cube(
    D: np.ndarray,
    w0: np.ndarray,
    cfg: CleanConfig,
    progress: ProgressFn | None = None,
    want_residual: bool = False,
) -> CleanResult:
    """Run the iterative cleaner on a preprocessed cube.

    D: (nsub, nchan, nbin) float32 — pscrunched, baseline-removed,
    dedispersed.  w0: (nsub, nchan) float32 original weights.

    With ``cfg.fused`` (jax backend only) the whole loop runs as one device
    dispatch; the per-loop ``iterations`` records (and ``progress``
    callbacks — the reference's per-loop diff/rfi_frac prints,
    iterative_cleaner.py:132-133) are derived *post hoc* from the kernel's
    on-device weight-history ring buffer, so ``--fused`` without ``-q``
    prints the same loop lines as the stepwise path.  Only ``duration_s``
    stays 0 — a single dispatch has no per-iteration host wall-clock.

    Cubes whose working set exceeds one device's HBM are automatically routed
    through the (sp, tp)-sharded kernel when more devices are available
    (BASELINE.md config #5; parallel/autoshard.py); when sharding is
    unavailable (one chip — the v5e-1 north-star target) or unsuitable
    (--x64, --unload_res, mesh-indivisible dims) the cube streams through
    the single-device chunked backend instead (parallel/chunked.py) — a
    stepwise path, so progress / history / residual all keep working.
    """
    if cfg.backend == "jax" and D.shape[-1] < 3:
        import warnings

        warnings.warn(
            "mask parity vs the numpy oracle is not guaranteed below 3 "
            "phase bins: numpy.ma computes a mixed f32/f64 diagnostic "
            "pipeline (3 of 4 promoted to f64) and a centred 2-bin profile "
            "is structurally tied, so the device pipeline's MAD/tie "
            "classifications can flip at any uniform precision — f32 "
            "default and --x64 alike (SURVEY.md §8.L9)", stacklevel=2)
    try:
        scan_cap = float(os.environ.get("ICT_PARITY_SCAN_MAX_BYTES", 4e9))
    except ValueError:
        scan_cap = 4e9  # malformed knob: advisory scan, not a crash
    if cfg.backend == "jax" and D.nbytes <= scan_cap:
        # Dynamic-range bound of the parity guarantee: beyond ~sqrt(f32max)
        # the oracle's MIXED pipeline bifurcates — its f32 fit overflows
        # <t,t> to inf (degenerate amp=1 branch) while its f64-promoted
        # ma.std stays finite — a combination no uniform-precision device
        # pipeline (f32 default or --x64) reproduces (SURVEY §8.L9).
        # min/max instead of abs().max(): no copy of a possibly >HBM cube.
        # nanmin/nanmax so a stray NaN cannot silently suppress the check
        # for a co-present finite spike (still copy-free on a >HBM cube).
        # The scan is two sequential host passes over the cube, so it is
        # capped (ICT_PARITY_SCAN_MAX_BYTES, default 4 GB; raise or 'inf' to
        # scan always): on the >HBM chunked route it would otherwise add a
        # multi-GB host scan per archive purely to decide a warning —
        # corruption at that magnitude (>1e17) is vanishingly rare in real
        # f32 archives and the warning is advisory, not load-bearing.
        peak = max(-float(np.nanmin(D)), float(np.nanmax(D))) * max(
            1.0, abs(float(np.nanmax(w0))), abs(float(np.nanmin(w0))))
        # Only FINITE magnitudes in the overflow band bifurcate the mixed
        # pipeline; ±inf/NaN inputs poison both pipelines identically
        # (pinned by test_masks_identical_with_inf_samples) and stay quiet —
        # the one blind spot is an inf sample coexisting with a finite
        # overflow-band spike, undetectable without a filtered second pass.
        if np.isfinite(peak) and peak > 1e17:
            import warnings

            warnings.warn(
                f"data magnitude ~{peak:.1e} approaches the f32 dynamic "
                "range (squared residuals overflow beyond ~1.8e19, and the "
                "oracle's mixed f32/f64 pipeline bifurcates there); mask "
                "parity is not guaranteed at any device precision — inspect "
                "the input for corruption (SURVEY.md §8.L9)", stacklevel=2)
    chunk_block = None
    chunk_why = ""
    if cfg.backend == "jax" and cfg.chunk_block:
        # Explicit operator override: stream with this block size no matter
        # what the working-set estimate says (the escape hatch for hosts
        # where the estimate or the reported device memory is off).
        chunk_block = int(cfg.chunk_block)
        chunk_why = "--chunk_block override"
    elif cfg.backend == "jax" and cfg.auto_shard:
        from iterative_cleaner_tpu.parallel.autoshard import (
            chunk_block_subints,
            maybe_clean_sharded,
        )

        from iterative_cleaner_tpu.obs import events as _events
        from iterative_cleaner_tpu.obs.tracing import (
            compile_scope as _cscope,
            shape_bucket_label as _sbl,
        )

        with _cscope(_sbl(D.shape)):
            sharded = maybe_clean_sharded(D, w0, cfg, want_residual)
        if sharded is not None:
            if _events.active():
                _events.emit("clean_route", route="sharded",
                             shape=list(D.shape))
            from iterative_cleaner_tpu.obs import memory as _obs_memory

            _obs_memory.observe_route("sharded")
            # No x64/want_residual axes (maybe_clean_sharded declines both);
            # max_iter/pulse_region are statics of the sharded kernel.
            note_compiled_shape(
                (*D.shape, "sharded", cfg.max_iter,
                 tuple(cfg.pulse_region)))
            return sharded
        chunk_block = chunk_block_subints(D.shape, cfg)
        chunk_why = f"cube {tuple(D.shape)} exceeds device memory"
    if chunk_block is not None:
        # Announce the reroute and its caveats on both routes — an operator
        # forcing --chunk_block with --fused/--x64 gets the same honesty as
        # the automatic path.
        import sys

        notes = []
        if cfg.fused:
            notes.append("fused loop runs stepwise on this path")
        if cfg.x64:
            notes.append("x64: block-wise template accumulation "
                         "reorders the f64 sum, so bit-identity of "
                         "intermediate values vs the in-memory path "
                         "is not guaranteed")
        print(
            f"chunked clean: {chunk_why}; streaming {chunk_block}-subint "
            f"blocks through the device"
            f"{' (' + '; '.join(notes) + ')' if notes else ''}",
            file=sys.stderr)

    if want_residual and cfg.pallas is not False:
        # The Pallas kernel does not materialise the residual; fall back to
        # the XLA route for this request — for the tri-state auto default
        # (None) as well as an explicit True, because JaxCleaner resolves
        # auto WITHOUT the want_residual context (resolved BEFORE the
        # compile-cache key below so the key matches the executable
        # actually compiled; run_fused applies the same fallback
        # internally).
        cfg = cfg.replace(pallas=False)
    if want_residual and cfg.incremental_template and chunk_block is None:
        # Residual output must be bit-exact (dense templates): the sparse
        # path's ulp envelope is documented for scores only.  The chunked
        # route keeps incremental — its residual() dense-rebuilds anyway.
        cfg = cfg.replace(incremental_template=False)

    if cfg.backend == "jax":
        nsub, nchan, nbin = D.shape
        pr = tuple(cfg.pulse_region)
        # Keys mirror each route's actual static-arg surface (the axes that
        # compile distinct executable sets) because the empirical ~70-compile
        # segfault budget is per executable, not per cube shape — an axis the
        # route does not specialize on would double-count one executable and
        # fire the cache drop early.
        if chunk_block is not None:
            # Chunked executables are keyed by the block slab shape, not the
            # cube: distinct-nsub cubes sharing one block size reuse one
            # executable set and must not count as distinct shapes.
            # Mirror ChunkedJaxCleaner's runtime resolution (tri-state
            # cfg.pallas + viability demotion) so the pallas axis reflects
            # the executable actually compiled.
            from iterative_cleaner_tpu.ops.pallas_kernels import (
                pallas_route_ok,
                resolve_use_pallas,
            )

            use_pallas = resolve_use_pallas(cfg, nbin)
            if use_pallas:
                use_pallas = pallas_route_ok(nbin)
            # The step loop always compiles the want_resid=False variant;
            # a residual request additionally compiles the want_resid=True
            # XLA variant in the lazy fetch (chunked.py) — count both.
            fps = [("chunked", use_pallas, cfg.x64, False,
                    cfg.incremental_template, pr)]
            if want_residual:
                fps.append(("chunked", False, cfg.x64, True, pr))
            slabs = [(min(chunk_block, nsub), nchan, nbin)]
            if nsub > chunk_block and nsub % chunk_block:
                slabs.append((nsub % chunk_block, nchan, nbin))
            for slab in slabs:
                for fp in fps:
                    note_compiled_shape((*slab, *fp))
        else:
            # Shared with the precompile warm path (which notes the same
            # key BEFORE warming, so a due cache drop lands before the
            # warm compiles rather than between warm and real call).
            note_compiled_shape(
                inmemory_route_key((nsub, nchan, nbin), cfg, want_residual))

    from iterative_cleaner_tpu.obs import events, forensics
    from iterative_cleaner_tpu.obs.tracing import (
        compile_scope,
        shape_bucket_label,
    )

    if cfg.fused and chunk_block is None:
        from iterative_cleaner_tpu.backends.jax_backend import run_fused

        if events.active():
            events.emit("clean_route", route="fused", shape=list(D.shape))
        with compile_scope(shape_bucket_label(D.shape)):
            out = run_fused(D, w0, cfg, want_residual=want_residual)
        from iterative_cleaner_tpu.obs import memory as obs_memory

        obs_memory.observe_route("fused")
        test, w_final, loops, done, _x, history = out[:6]
        history = list(history)
        infos = []
        for i in range(1, len(history)):
            info = _iteration_info(i, history[i - 1], history[i])
            if forensics.attribution_enabled():
                info.zaps_by_diagnostic = forensics.attribute_zaps(
                    D, w0, history[i - 1], history[i], cfg)
            infos.append(info)
            if progress is not None:
                progress(info)
            if events.active():
                events.emit("iteration", **forensics.iteration_record(info))
        return CleanResult(
            weights=w_final,
            test_results=test,
            loops=loops,
            converged=done,
            iterations=infos,
            history=history,
            residual=out[6] if want_residual else None,
            termination=forensics.termination_reason(done, history),
        )

    if chunk_block is not None:
        from iterative_cleaner_tpu.parallel.chunked import ChunkedJaxCleaner

        if events.active():
            events.emit("clean_route", route="chunked", shape=list(D.shape),
                        block=chunk_block, why=chunk_why)
        backend = ChunkedJaxCleaner(
            D, w0, cfg, block=chunk_block, keep_residual=want_residual)
    else:
        if events.active():
            events.emit("clean_route",
                        route="stepwise" if cfg.backend == "jax" else "numpy",
                        shape=list(D.shape))
        backend = make_backend(D, w0, cfg)
    state = LoopState.start(w0)
    with compile_scope(shape_bucket_label(D.shape)):
        state.run(backend, cfg.max_iter, progress=progress)
    if cfg.backend == "jax":
        from iterative_cleaner_tpu.obs import memory as obs_memory

        obs_memory.observe_route("chunked" if chunk_block is not None
                                 else "stepwise")

    residual = None
    if want_residual:
        r = backend.residual()
        residual = None if r is None else np.asarray(r)

    return state.result(residual=residual, timed=True)


def find_bad_parts(
    weights: np.ndarray, cfg: CleanConfig
) -> tuple[np.ndarray, int, int]:
    """Whole-subint / whole-channel sweep (reference
    iterative_cleaner.py:307-334).

    Both passes compute their zapped fraction from the same pre-sweep
    snapshot (the reference takes ``get_weights()`` once at :310), and both
    use a *strictly greater* comparison.  Returns (new_weights,
    n_bad_subints, n_bad_channels).
    """
    snapshot = np.asarray(weights)
    nsub, nchan = snapshot.shape
    out = snapshot.copy()

    bad_subints = (1.0 - np.count_nonzero(snapshot, axis=1) / float(nchan)) > cfg.bad_subint
    out[bad_subints, :] = 0.0
    bad_channels = (1.0 - np.count_nonzero(snapshot, axis=0) / float(nsub)) > cfg.bad_chan
    out[:, bad_channels] = 0.0
    return out, int(bad_subints.sum()), int(bad_channels.sum())
