"""iterative_cleaner_tpu — a TPU-native iterative "surgical" RFI cleaner framework.

A ground-up JAX/XLA re-design of the capabilities of the reference
``iterative_cleaner`` (bwmeyers/iterative_cleaner, a single-file numpy/psrchive
script): iterative template subtraction + robust outlier statistics over a
pulsar-archive data cube, with the whole per-iteration pipeline fused into one
jitted TPU kernel and multi-archive batches sharded over a device mesh.

Layer map (mirrors SURVEY.md §1, re-designed TPU-first):

  CLI / driver        iterative_cleaner_tpu.cli, .driver      (host)
  model               iterative_cleaner_tpu.models.surgical   (flagship cleaner)
  core loop           iterative_cleaner_tpu.core.cleaner      (backend-agnostic)
  backends            .backends.numpy_backend (oracle)        (executable spec)
                      .backends.jax_backend   (TPU kernel)    (jit / while_loop)
  ops                 .ops.*                  (stats, template fit, preprocess)
  parallel            .parallel.*             (mesh, shard_map, batch pmap)
  io                  .io.*                   (NPZ canonical, psrchive optional)
"""

from iterative_cleaner_tpu.config import CleanConfig
from iterative_cleaner_tpu.io.base import Archive

__version__ = "0.1.0"

__all__ = ["CleanConfig", "Archive", "__version__"]
