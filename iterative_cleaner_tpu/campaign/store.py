"""Spool-persisted campaign state: ``<spool>/campaigns/<id>/``.

One directory per campaign holding ``manifest.json`` (the compiled
campaign record — identity, tenant, entry list with pinned idempotency
keys) and one ``a<index>.json`` status record per archive.  Every write
is .part-rename atomic under one lock (the service.jobs.JobSpool
discipline), so a router killed mid-update never leaves a truncated
record, and a restarted router rehydrates open campaigns and resumes
only their non-terminal archives.
"""

from __future__ import annotations

import json
import os
import threading

#: Archive lifecycle: pending -> placed -> done | error | cancelled.
ARCHIVE_STATES = ("pending", "placed", "done", "error", "cancelled")
ARCHIVE_TERMINAL = ("done", "error", "cancelled")

#: Campaign lifecycle: open -> done | failed | cancelled.
CAMPAIGN_TERMINAL = ("done", "failed", "cancelled")


class CampaignStore:
    """Directory of per-campaign subdirectories; the orchestrator's
    durable state.  All mutation goes through the save methods under one
    lock — records are tiny, and serialized writes keep the
    rename-atomic invariant simple across the poll and HTTP threads."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _dir(self, campaign_id: str) -> str | None:
        """Campaign directory for an id, or None for anything that is
        not a plain directory name — ids come straight off the HTTP path
        (GET /campaigns/<id>), so '../'-shaped ids must never resolve
        outside the spool (the JobSpool._manifest guard)."""
        cid = str(campaign_id)
        if os.path.basename(cid) != cid or not cid or cid.startswith("."):
            return None
        return os.path.join(self.root, cid)

    def _write(self, path: str, record: dict) -> None:
        tmp = f"{path}.part"
        with self._lock:
            with open(tmp, "w") as fh:
                json.dump(record, fh, indent=1)
                fh.write("\n")
            os.replace(tmp, path)

    @staticmethod
    def _read(path: str) -> dict | None:
        try:
            with open(path) as fh:
                d = json.load(fh)
            return d if isinstance(d, dict) else None
        # TypeError/ValueError cover foreign or truncated JSON: one
        # unreadable file degrades to "no record", never crash-loops the
        # startup rehydrate (the JobSpool.get convention).
        except (OSError, ValueError, TypeError):
            return None

    def save_campaign(self, record: dict) -> None:
        d = self._dir(record["id"])
        if d is None:
            raise ValueError(f"unsaveable campaign id {record['id']!r}")
        os.makedirs(d, exist_ok=True)
        self._write(os.path.join(d, "manifest.json"), record)

    def save_archive(self, campaign_id: str, record: dict) -> None:
        d = self._dir(campaign_id)
        if d is None:
            raise ValueError(f"unsaveable campaign id {campaign_id!r}")
        self._write(os.path.join(d, f"a{int(record['index']):05d}.json"),
                    record)

    def load_campaign(self, campaign_id: str) -> dict | None:
        d = self._dir(campaign_id)
        if d is None:
            return None
        rec = self._read(os.path.join(d, "manifest.json"))
        if rec is None or rec.get("id") != campaign_id:
            # The inner id must round-trip to the directory name — a
            # mismatched record would duplicate the campaign under a
            # second identity on the next save.
            return None
        return rec

    def load_archives(self, campaign_id: str) -> list[dict]:
        """Per-archive status records in index order; entries whose
        status file is missing or unreadable are simply absent (the
        rehydrate path re-seeds them as pending from the manifest)."""
        d = self._dir(campaign_id)
        if d is None or not os.path.isdir(d):
            return []
        out = []
        for name in sorted(os.listdir(d)):
            if not (name.startswith("a") and name.endswith(".json")):
                continue
            rec = self._read(os.path.join(d, name))
            if rec is not None and "index" in rec:
                out.append(rec)
        return out

    def list_ids(self) -> list[str]:
        """Every persisted campaign id, in id (== creation) order."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        return [n for n in names
                if self._dir(n) is not None
                and os.path.isfile(os.path.join(self.root, n,
                                                "manifest.json"))]

    def sweep_parts(self) -> None:
        """Remove orphaned atomic-write temps (a router killed between
        the .part write and the rename).  Runs once at rehydrate, before
        any writer thread exists — the JobSpool.trim discipline."""
        for cid in self.list_ids():
            d = self._dir(cid)
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for name in names:
                if name.endswith(".part"):
                    try:
                        os.remove(os.path.join(d, name))
                    except OSError:
                        pass
