"""``ict-clean campaign MANIFEST`` — the campaign follow client.

Reads a manifest JSON file, POSTs it to a fleet router, then follows the
campaign's progress (one line whenever the archive-state counts move)
until it settles terminally.  Exit status is the campaign verdict: 0
only when the campaign finished ``done`` with zero failed archives —
scriptable exactly like a solo ``ict-clean`` batch.

The client is deliberately thin: all state lives on the router (spool-
persisted), so killing and rerunning the follow loop against the same
campaign id — or resubmitting the same manifest after a router restart —
never re-cleans anything (docs/SERVING.md "Campaigns").
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request


def _http(url: str, payload: dict | None = None,
          timeout_s: float = 10.0) -> tuple[int, dict]:
    """One JSON round-trip; (status, body-dict).  HTTP error statuses
    come back as values (their JSON bodies carry the router's message),
    transport failures raise OSError for the caller to report."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read().decode() or "{}")
        except ValueError:
            body = {}
        return exc.code, body


def _progress_line(view: dict) -> str:
    a = view.get("archives", {})
    return (f"campaign {view.get('id', '?')} [{view.get('state', '?')}] "
            f"{a.get('done', 0)}/{a.get('total', 0)} done, "
            f"{a.get('placed', 0)} running, {a.get('pending', 0)} pending, "
            f"{a.get('error', 0)} failed")


def campaign_main(argv: list[str]) -> int:
    p = argparse.ArgumentParser(
        prog="iterative-cleaner-tpu campaign",
        description="submit a campaign manifest to a fleet router and "
                    "follow it to completion (docs/SERVING.md 'Campaigns')")
    p.add_argument("manifest", help="campaign manifest JSON file")
    p.add_argument("--router", default="http://127.0.0.1:8790",
                   metavar="URL", help="fleet router base URL "
                   "(default http://127.0.0.1:8790)")
    p.add_argument("--poll_s", type=float, default=2.0, metavar="S",
                   help="seconds between progress polls (default 2)")
    p.add_argument("--timeout_s", type=float, default=0.0, metavar="S",
                   help="give up (exit 1, campaign keeps running server-"
                        "side) after this many seconds; 0 = follow forever")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="print the final GET /campaigns/<id> view (QA "
                        "roll-up + cost showback) as JSON on stdout")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="no progress lines, just the verdict")
    args = p.parse_args(argv)

    try:
        with open(args.manifest) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as exc:
        print(f"error: unreadable manifest {args.manifest!r}: {exc}",
              file=sys.stderr)
        return 2

    base = args.router.rstrip("/")
    try:
        code, body = _http(f"{base}/campaigns", payload=manifest)
    except OSError as exc:
        print(f"error: router unreachable at {base}: {exc}",
              file=sys.stderr)
        return 1
    if code != 200:
        print(f"error: router rejected the manifest ({code}): "
              f"{body.get('error', body)}", file=sys.stderr)
        return 2
    cid = body.get("id", "")
    if not args.quiet:
        print(_progress_line(body), file=sys.stderr)

    deadline = time.monotonic() + args.timeout_s if args.timeout_s else None
    last = ""
    view = body
    while view.get("state") == "open":
        if deadline is not None and time.monotonic() >= deadline:
            print(f"error: campaign {cid} still open after "
                  f"{args.timeout_s:g}s (it keeps running; re-follow with "
                  f"GET {base}/campaigns/{cid})", file=sys.stderr)
            return 1
        time.sleep(args.poll_s)
        try:
            code, view = _http(f"{base}/campaigns/{cid}")
        except OSError as exc:
            # A router bounce mid-follow is survivable: the spool has the
            # campaign, so keep polling until the deadline says stop.
            if not args.quiet:
                print(f"campaign {cid}: router unreachable ({exc}); "
                      "retrying", file=sys.stderr)
            continue
        if code != 200:
            print(f"error: campaign {cid} lookup failed ({code})",
                  file=sys.stderr)
            return 1
        line = _progress_line(view)
        if not args.quiet and line != last:
            print(line, file=sys.stderr)
            last = line

    errors = view.get("archives", {}).get("error", 0)
    cost = view.get("cost", {}) or {}
    outliers = (view.get("rollup", {}) or {}).get("outliers", []) or []
    if not args.quiet:
        print(f"campaign {cid} finished {view.get('state')}: "
              f"{errors} failed, "
              f"{cost.get('device_s', 0.0):.3f} device-s "
              f"({cost.get('avoided_device_s', 0.0):.3f} avoided, "
              f"{cost.get('cache_hits', 0)} cache hits), "
              f"{len(outliers)} QA outlier(s)", file=sys.stderr)
        for rec in view.get("archive_records", []):
            if rec.get("state") == "error":
                print(f"  FAILED a{rec.get('index'):05d} "
                      f"{rec.get('path')}: {rec.get('error')}",
                      file=sys.stderr)
        for out in outliers:
            print(f"  OUTLIER a{out.get('index'):05d} {out.get('path')}: "
                  f"zap_frac={out.get('zap_frac')} "
                  f"({','.join(out.get('reasons', []))})", file=sys.stderr)
    if args.as_json:
        print(json.dumps(view, sort_keys=True))
    return 0 if view.get("state") == "done" and not errors else 1
