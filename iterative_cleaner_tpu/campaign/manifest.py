"""Campaign manifest grammar: validation, glob expansion, and the
deterministic per-archive idempotency keys.

A manifest is one JSON object::

    {
      "name": "survey-2026A",              // optional display label
      "tenant": "survey",                  // showback identity ("default")
      "archives": ["/data/a.npz", ...],    // explicit archive paths
      "globs": ["/data/night1/*.npz"],     // expanded (sorted) at POST time
      "config": {"max_iter": 12},          // PROVENANCE ONLY — recorded on
                                           // the campaign, never shipped to
                                           // replicas (replicas own their
                                           // CleanConfig; the cache-salt
                                           // discipline, docs/SERVING.md)
      "overrides": {                       // optional per-archive knobs,
        "/data/a.npz": {"shape": [8, 32, 128], "audit": true}
      },                                   // limited to the POST /jobs
                                           // fields: shape/audit/profile
      "max_inflight": 8,                   // per-campaign placement pacing
      "synthetic": false                   // canary micro-campaigns only:
                                           // stamps every archive job
                                           // synthetic=true (fleet/canary.py)
    }

``archives`` keeps submission order and MAY repeat a path — duplicates
get distinct idempotency keys (the key includes the entry index) so they
become separate placements that resolve born-terminal out of the fleet
result cache instead of idempotency-deduping into one job.  The
per-archive key is a pure function of (campaign id, index, path):
restart-resume and failover re-submissions regenerate the exact same
key, which is what makes them exactly-once by construction.
"""

from __future__ import annotations

import glob as _glob
import hashlib
import time
import uuid

#: Default per-campaign ceiling on simultaneously open placements —
#: pacing, not admission: the router's WFQ/quota machinery stays the
#: real arbiter, this just keeps one campaign from parking thousands of
#: placements (and their slots) at once.
DEFAULT_MAX_INFLIGHT = 8

#: Per-archive override fields honored (the POST /jobs payload surface).
OVERRIDE_FIELDS = ("shape", "audit", "profile")


def new_campaign_id() -> str:
    """Time-sortable unique id (the service.jobs.new_job_id idiom):
    lexicographic order of ids == creation order across a spool replay."""
    return f"c{int(time.time() * 1000):013d}-{uuid.uuid4().hex[:6]}"


def archive_idem_key(campaign_id: str, index: int, path: str) -> str:
    """The deterministic campaign-scoped idempotency key for one archive
    entry.  Includes the ENTRY INDEX so a path listed twice yields two
    distinct keys (duplicates must reach the fleet result cache, not the
    idempotency dedupe), and a path digest so keys stay opaque-safe for
    HTTP/file use whatever the path contains."""
    digest = hashlib.sha256(path.encode()).hexdigest()[:12]
    return f"campaign-{campaign_id}-{int(index):05d}-{digest}"


def _clean_overrides(raw: dict) -> dict:
    """One archive's override dict, restricted to the POST /jobs fields
    the replicas honor; anything else is a manifest error (silently
    dropping a knob the operator typed would misclean quietly)."""
    if not isinstance(raw, dict):
        raise ValueError("overrides entries must be JSON objects")
    unknown = sorted(set(raw) - set(OVERRIDE_FIELDS))
    if unknown:
        raise ValueError(
            f"unsupported override field(s) {unknown}; per-archive "
            f"overrides are limited to {list(OVERRIDE_FIELDS)} — cleaning "
            "config belongs to the replicas (docs/SERVING.md 'Campaigns')")
    out: dict = {}
    if "shape" in raw:
        shape = raw["shape"]
        if (not isinstance(shape, (list, tuple)) or len(shape) != 3):
            raise ValueError(f"override shape must be [nsub, nchan, nbin], "
                             f"got {shape!r}")
        out["shape"] = [int(v) for v in shape]
    for flag in ("audit", "profile"):
        if flag in raw:
            out[flag] = bool(raw[flag])
    return out


def compile_manifest(raw: dict, campaign_id: str | None = None) -> dict:
    """Validate one manifest object and compile it into the campaign
    record the store persists: ``{"id", "name", "tenant", "state",
    "created_s", "max_inflight", "config", "entries": [{"index", "path",
    "idem_key", "overrides"}, ...]}``.  Raises ValueError with an
    operator-actionable message on any grammar violation (the
    parse_tenant_specs convention)."""
    if not isinstance(raw, dict):
        raise ValueError("a campaign manifest must be a JSON object")
    unknown = sorted(set(raw) - {"name", "tenant", "archives", "globs",
                                 "config", "overrides", "max_inflight",
                                 "synthetic"})
    if unknown:
        raise ValueError(f"unknown manifest field(s) {unknown}; see "
                         "docs/SERVING.md 'Campaigns' for the grammar")
    cid = campaign_id or new_campaign_id()
    name = str(raw.get("name", "") or cid)
    tenant = str(raw.get("tenant", "") or "default")
    # Canary micro-campaigns (fleet/canary.py): every archive job is
    # stamped synthetic=true so the probe stays out of capacity demand,
    # tenant quotas, and cost showback.
    synthetic = bool(raw.get("synthetic", False))
    config = raw.get("config") or {}
    if not isinstance(config, dict):
        raise ValueError("manifest config must be a JSON object "
                         "(recorded as provenance only)")

    paths: list[str] = []
    archives = raw.get("archives", [])
    if not isinstance(archives, list) or not all(
            isinstance(p, str) and p for p in archives):
        raise ValueError("manifest archives must be a list of path strings")
    paths.extend(archives)
    globs = raw.get("globs", [])
    if not isinstance(globs, list) or not all(
            isinstance(g, str) and g for g in globs):
        raise ValueError("manifest globs must be a list of glob strings")
    for pattern in globs:
        # Sorted expansion: the entry order (and therefore every
        # idempotency key) is deterministic across restarts and hosts.
        paths.extend(sorted(_glob.glob(pattern)))
    if not paths:
        raise ValueError("manifest names no archives (empty archives list "
                         "and no glob matched anything)")

    overrides = raw.get("overrides") or {}
    if not isinstance(overrides, dict):
        raise ValueError("manifest overrides must map archive path -> "
                         "override object")
    stray = sorted(set(overrides) - set(paths))
    if stray:
        raise ValueError(f"overrides name path(s) not in the campaign: "
                         f"{stray}")

    try:
        max_inflight = int(raw.get("max_inflight", DEFAULT_MAX_INFLIGHT))
    except (TypeError, ValueError) as exc:
        raise ValueError(f"bad max_inflight {raw.get('max_inflight')!r}; "
                         "want an int >= 1") from exc
    if max_inflight < 1:
        raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")

    entries = [{
        "index": i,
        "path": p,
        "idem_key": archive_idem_key(cid, i, p),
        "overrides": _clean_overrides(overrides.get(p, {})),
    } for i, p in enumerate(paths)]
    return {
        "id": cid,
        "name": name,
        "tenant": tenant,
        "synthetic": synthetic,
        "state": "open",
        "created_s": round(time.time(), 3),
        "finished_s": 0.0,
        "max_inflight": max_inflight,
        "config": config,
        "n_archives": len(entries),
        "entries": entries,
    }
