"""The campaign orchestrator: a state machine driven from the fleet
router's poll tick.

One instance lives on the router.  Each :meth:`tick` (poll_tick's
campaign step) advances every open campaign: observe placed archives
through :meth:`~..fleet.router.FleetRouter.job_manifest` (the same
status-refresh path ordinary placements use — failover, death handling
and the synthetic "replica unreachable" pending view all come for free),
fold terminal results into the spool, and submit pending archives
through :meth:`~..fleet.router.FleetRouter.place_job` under their pinned
campaign-scoped idempotency keys, paced by the campaign's
``max_inflight``.

Restart-resume: the constructor rehydrates every persisted campaign;
open campaigns demote their ``placed`` archives back to ``pending`` (the
service.jobs.recover idiom — the placement table died with the old
router) and the next ticks re-place them under the SAME keys, so an
archive whose job already finished on a replica dedupes against the
replica-side idempotency map instead of running again, and terminal
archives are never resubmitted at all.

Locking: one orchestrator lock guards the in-memory campaign/archive
tables, ordered strictly AFTER the router's RLock — this module never
calls back into the router (place_job, job_manifest) while holding its
own lock; ticks snapshot under the lock, call out unlocked, then
re-acquire to record.
"""

from __future__ import annotations

import sys
import threading
import time

from iterative_cleaner_tpu.campaign import rollup
from iterative_cleaner_tpu.campaign.manifest import compile_manifest
from iterative_cleaner_tpu.campaign.store import (
    ARCHIVE_TERMINAL,
    CAMPAIGN_TERMINAL,
    CampaignStore,
)
from iterative_cleaner_tpu.obs import events

#: Consecutive 404 status reads before a placed archive is re-queued
#: under its pinned key (its placement was trimmed from the router
#: table); immediate on rehydrate, where the table is known-gone.
MISSING_BEFORE_REQUEUE = 2

#: Re-queue ceiling per archive: a placement that keeps vanishing is a
#: real fault (replica spool clearing, placement-table thrash), and the
#: archive fails terminally instead of cycling forever.
MAX_REQUEUES = 5

#: A ``done`` manifest can be HTTP-visible a beat before the dispatch
#: worker finalizes its CostRecord (the run_fleet_smoke conservation
#: lane's retry rationale); hold a done archive open this many extra
#: polls waiting for cost to land before folding it without one.
COST_SETTLE_POLLS = 5

#: Terminal campaigns kept in memory (list/GET views); the spool keeps
#: everything — the placement_keep bounded-memory rationale.
KEEP_TERMINAL = 50

#: Gauge states always pre-registered (the pre-registration-at-0 lesson:
#: docs gates and gt-threshold rules need the series before first use).
_ARCHIVE_GAUGE_STATES = ("pending", "placed", "done", "error", "cancelled")


class CampaignOrchestrator:
    """Owns every campaign's lifecycle on one router; constructed by
    FleetRouter.__init__ and ticked from its poll loop."""

    def __init__(self, store: CampaignStore, router, quiet: bool = True,
                 ) -> None:
        self.store = store
        self._router = router       # back-ref; never called under _lock
        self.quiet = quiet
        self._lock = threading.Lock()
        # cid -> campaign record (the persisted manifest.json shape).
        self._campaigns: dict[str, dict] = {}  # ict: guarded-by(self._lock)
        # cid -> {index -> archive status record}.
        self._archives: dict[str, dict[int, dict]] = {}  # ict: guarded-by(self._lock)
        self._rehydrate()

    # --- rehydration (router start) ---

    def _rehydrate(self) -> None:
        """Reload every persisted campaign; open ones resume — terminal
        archives stay terminal (never resubmitted), placed ones demote
        to pending for re-placement under their pinned keys."""
        self.store.sweep_parts()
        for cid in self.store.list_ids():
            camp = self.store.load_campaign(cid)
            if camp is None:
                continue
            records: dict[int, dict] = {}
            if camp.get("state") not in CAMPAIGN_TERMINAL:
                on_disk = {int(r["index"]): r
                           for r in self.store.load_archives(cid)}
                for entry in camp.get("entries", []):
                    idx = int(entry["index"])
                    rec = on_disk.get(idx) or self._seed_archive(entry)
                    if rec.get("state") == "placed":
                        # The old router's placement table died with it;
                        # the pinned idempotency key makes the re-place
                        # dedupe instead of re-clean.
                        rec["state"] = "pending"
                        rec["requeues"] = int(rec.get("requeues", 0)) + 1
                        self.store.save_archive(cid, rec)
                    records[idx] = rec
                if not self.quiet:
                    open_n = sum(1 for r in records.values()
                                 if r["state"] not in ARCHIVE_TERMINAL)
                    print(f"ict-fleet: campaign {cid} rehydrated "
                          f"({open_n}/{len(records)} archives to resume)",
                          file=sys.stderr)
            with self._lock:
                self._campaigns[cid] = camp
                self._archives[cid] = records
        self._trim()

    @staticmethod
    def _seed_archive(entry: dict) -> dict:
        return {
            "index": int(entry["index"]),
            "path": str(entry["path"]),
            "idem_key": str(entry["idem_key"]),
            "overrides": dict(entry.get("overrides") or {}),
            "state": "pending",
            "job_id": "",
            "trace_id": "",
            "attempts": 0,
            "requeues": 0,
            "missing_polls": 0,
            "cost_polls": 0,
            "error": "",
            "out_path": "",
            "served_by": "",
            "termination": "",
            "replica_id": "",
            "quality": {},
            "cost": {},
            "finished_s": 0.0,
        }

    # --- the lifecycle API (HTTP handlers) ---

    def create(self, raw_manifest: dict) -> dict:
        """POST /campaigns: compile, persist, register.  Placement
        begins on the next poll tick (submission stays on the poll
        thread, the one-writer discipline).  Raises ValueError on a
        grammar violation (-> 400)."""
        camp = compile_manifest(raw_manifest)
        records = {int(e["index"]): self._seed_archive(e)
                   for e in camp["entries"]}
        self.store.save_campaign(camp)
        with self._lock:
            self._campaigns[camp["id"]] = camp
            self._archives[camp["id"]] = records
        if events.active():
            events.emit("campaign_created", campaign_id=camp["id"],
                        name=camp["name"], tenant=camp["tenant"],
                        archives=camp["n_archives"])
        if not self.quiet:
            print(f"ict-fleet: campaign {camp['id']} created "
                  f"({camp['n_archives']} archives, tenant "
                  f"{camp['tenant']!r})", file=sys.stderr)
        return self._summary_row(camp, records)

    def list(self) -> list[dict]:
        with self._lock:
            rows = [(dict(c), dict(self._archives.get(cid, {})))
                    for cid, c in self._campaigns.items()]
        return [self._summary_row(c, recs) for c, recs in rows]

    def get(self, campaign_id: str) -> dict | None:
        """GET /campaigns/<id>: the full view — per-archive states, the
        QA roll-up, and the cost showback."""
        with self._lock:
            camp = self._campaigns.get(campaign_id)
            records = dict(self._archives.get(campaign_id, {}))
        if camp is None:
            # Trimmed from memory but maybe still on the spool.
            camp = self.store.load_campaign(campaign_id)
            if camp is None:
                return None
            records = {int(r["index"]): r
                       for r in self.store.load_archives(campaign_id)}
        recs = [records[i] for i in sorted(records)]
        view = self._summary_row(camp, records)
        view["config"] = camp.get("config", {})
        view["max_inflight"] = camp.get("max_inflight")
        view["archive_records"] = [{
            "index": r["index"], "path": r["path"], "state": r["state"],
            "job_id": r.get("job_id", ""),
            "idem_key": r.get("idem_key", ""),
            "attempts": r.get("attempts", 0),
            "served_by": r.get("served_by", ""),
            "replica_id": r.get("replica_id", ""),
            "out_path": r.get("out_path", ""),
            "error": r.get("error", ""),
        } for r in recs]
        view["rollup"] = rollup.fold_quality(recs)
        view["cost"] = rollup.fold_cost(recs)
        return view

    def cancel(self, campaign_id: str) -> dict | None:
        """POST /campaigns/<id>/cancel: pending archives cancel
        immediately; placed ones finish on their replicas (accepted work
        is never yanked — the drain semantics) and keep being observed
        until the campaign settles terminally cancelled."""
        with self._lock:
            camp = self._campaigns.get(campaign_id)
            if camp is None:
                return None
            records = self._archives.get(campaign_id, {})
            if camp["state"] not in CAMPAIGN_TERMINAL:
                camp["state"] = "cancelled"
                for rec in records.values():
                    if rec["state"] == "pending":
                        rec["state"] = "cancelled"
                        rec["finished_s"] = round(time.time(), 3)
                        self.store.save_archive(campaign_id, rec)
                if not any(r["state"] == "placed"
                           for r in records.values()):
                    camp["finished_s"] = round(time.time(), 3)
                self.store.save_campaign(camp)
            row = self._summary_row(dict(camp), dict(records))
        if events.active():
            events.emit("campaign_cancelled", campaign_id=campaign_id)
        return row

    # --- the poll-tick step ---

    def tick(self) -> None:
        """Advance every campaign that still has work: observe placed
        archives, submit pending ones, finish settled campaigns.  Runs
        on the router's poll thread only."""
        with self._lock:
            active = [cid for cid, c in self._campaigns.items()
                      if c["state"] == "open"
                      or any(r["state"] == "placed"
                             for r in self._archives.get(cid, {}).values())]
        for cid in active:
            self._observe(cid)
            self._submit_pending(cid)
            self._maybe_finish(cid)

    def _observe(self, cid: str) -> None:
        with self._lock:
            placed = [dict(r) for r in self._archives.get(cid, {}).values()
                      if r["state"] == "placed"]
        for rec in placed:
            code, manifest = self._router.job_manifest(rec["job_id"])
            if code == 404:
                self._requeue(cid, rec["index"])
                continue
            if code != 200 or manifest.get("state") not in ("done", "error"):
                continue   # still open (or synthetic pending) — next tick
            if (manifest.get("state") == "done"
                    and not manifest.get("cost")
                    and rec.get("cost_polls", 0) < COST_SETTLE_POLLS):
                # The manifest can turn done a beat before the worker
                # persists its CostRecord; hold for a few polls so the
                # showback fold doesn't under-report.
                with self._lock:
                    live = self._archives.get(cid, {}).get(rec["index"])
                    if live is not None and live["state"] == "placed":
                        live["cost_polls"] = live.get("cost_polls", 0) + 1
                continue
            self._record_terminal(cid, rec["index"], manifest)

    def _requeue(self, cid: str, index: int) -> None:
        """A placed archive the router no longer knows (trimmed table,
        restarted router): back to pending under the SAME pinned key —
        bounded, then terminally failed."""
        with self._lock:
            rec = self._archives.get(cid, {}).get(index)
            if rec is None or rec["state"] != "placed":
                return
            rec["missing_polls"] = rec.get("missing_polls", 0) + 1
            if rec["missing_polls"] < MISSING_BEFORE_REQUEUE:
                return
            rec["missing_polls"] = 0
            rec["requeues"] = int(rec.get("requeues", 0)) + 1
            if rec["requeues"] > MAX_REQUEUES:
                rec["state"] = "error"
                rec["error"] = (f"placement lost {rec['requeues']} times "
                                "(replica spool cleared / placement table "
                                "thrash); giving up")
                rec["finished_s"] = round(time.time(), 3)
            else:
                rec["state"] = "pending"
            self.store.save_archive(cid, rec)

    def _record_terminal(self, cid: str, index: int, manifest: dict) -> None:
        updates = {
            "state": str(manifest.get("state", "error")),
            "error": str(manifest.get("error", "") or ""),
            "out_path": str(manifest.get("out_path", "") or ""),
            "served_by": str(manifest.get("served_by", "") or ""),
            "termination": str(manifest.get("termination", "") or ""),
            "replica_id": str(manifest.get("replica_id", "") or ""),
            "quality": (manifest.get("quality")
                        if isinstance(manifest.get("quality"), dict)
                        else {}),
            "cost": (manifest.get("cost")
                     if isinstance(manifest.get("cost"), dict) else {}),
            "finished_s": round(time.time(), 3),
        }
        with self._lock:
            rec = self._archives.get(cid, {}).get(index)
            if rec is None or rec["state"] in ARCHIVE_TERMINAL:
                return
            rec.update(updates)
            self.store.save_archive(cid, rec)
            tenant = self._campaigns.get(cid, {}).get("tenant", "")
        if updates["state"] == "error" and events.active():
            events.emit("campaign_archive_error", campaign_id=cid,
                        archive_index=index, tenant=tenant,
                        error=updates["error"])

    def _submit_pending(self, cid: str) -> None:
        # Imported here, not at module top: fleet/__init__ imports the
        # router, which constructs this orchestrator — a module-level
        # import back into fleet would be circular.
        from iterative_cleaner_tpu.fleet.client import ReplicaRefused
        from iterative_cleaner_tpu.fleet.tenants import QuotaExceeded
        with self._lock:
            camp = self._campaigns.get(cid)
            records = self._archives.get(cid, {})
            if camp is None or camp["state"] != "open":
                return
            open_n = sum(1 for r in records.values()
                         if r["state"] == "placed")
            budget = max(int(camp.get("max_inflight", 1)) - open_n, 0)
            todo = [dict(r) for r in
                    sorted(records.values(), key=lambda r: r["index"])
                    if r["state"] == "pending"][:budget]
            tenant = str(camp.get("tenant", "") or "default")
            synthetic = bool(camp.get("synthetic", False))
        for rec in todo:
            payload = {
                "path": rec["path"],
                "idempotency_key": rec["idem_key"],
                "tenant": tenant,
            }
            if synthetic:
                # Canary campaigns (fleet/canary.py): the flag rides
                # every archive job end-to-end, keeping the probe out of
                # the demand/quota/cost planes it measures.
                payload["synthetic"] = True
            payload.update(rec.get("overrides") or {})
            trace_id = rec.get("trace_id") or events.new_trace_id()
            try:
                reply = self._router.place_job(payload, tenant, trace_id)
            except QuotaExceeded:
                break        # admission says no — next tick retries
            except ReplicaRefused as exc:
                # The fleet itself rejected the archive (e.g. --root
                # refusal, bad path): terminal, not retryable.
                self._fail_archive(cid, rec["index"], str(exc))
                continue
            except Exception as exc:  # noqa: BLE001
                # FleetBusy (no slot / everyone draining) and transport
                # surprises both mean "not now": stop submitting this
                # tick, the archive stays pending.
                if not self.quiet:
                    print(f"ict-fleet: campaign {cid} pausing submissions "
                          f"this tick ({exc})", file=sys.stderr)
                break
            self._note_placed(cid, rec["index"], trace_id, reply)

    def _note_placed(self, cid: str, index: int, trace_id: str,
                     reply: dict) -> None:
        job_id = str(reply.get("id", "") or "")
        with self._lock:
            rec = self._archives.get(cid, {}).get(index)
            if rec is None or rec["state"] != "pending":
                return
            rec["job_id"] = job_id
            rec["trace_id"] = trace_id
            rec["attempts"] = int(rec.get("attempts", 0)) + 1
            rec["state"] = "placed"
            rec["missing_polls"] = 0
            self.store.save_archive(cid, rec)
        if reply.get("state") in ("done", "error"):
            # Born terminal: a fleet-cache hit, or a replica-side
            # idempotency dedupe against an already-finished job (the
            # restart-resume path) — fold it now, no status poll needed.
            self._record_terminal(cid, index, reply)

    def _maybe_finish(self, cid: str) -> None:
        with self._lock:
            camp = self._campaigns.get(cid)
            records = self._archives.get(cid, {})
            if camp is None or camp["state"] in CAMPAIGN_TERMINAL:
                # A cancelled campaign still settles its finished_s once
                # the last placed archive lands.
                if (camp is not None and camp["state"] == "cancelled"
                        and not camp.get("finished_s")
                        and not any(r["state"] == "placed"
                                    for r in records.values())):
                    camp["finished_s"] = round(time.time(), 3)
                    self.store.save_campaign(camp)
                return
            if any(r["state"] not in ARCHIVE_TERMINAL
                   for r in records.values()):
                return
            errors = sum(1 for r in records.values()
                         if r["state"] == "error")
            camp["state"] = "failed" if errors else "done"
            camp["finished_s"] = round(time.time(), 3)
            self.store.save_campaign(camp)
            state, name, total = camp["state"], camp["name"], len(records)
        self._trim()
        if events.active():
            events.emit("campaign_finished", campaign_id=cid,
                        state=state, archives=total, errors=errors)
        if not self.quiet:
            print(f"ict-fleet: campaign {cid} ({name}) finished "
                  f"{state} ({total - errors}/{total} archives clean)",
                  file=sys.stderr)

    def _fail_archive(self, cid: str, index: int, error: str) -> None:
        with self._lock:
            rec = self._archives.get(cid, {}).get(index)
            if rec is None or rec["state"] in ARCHIVE_TERMINAL:
                return
            rec["state"] = "error"
            rec["error"] = error
            rec["finished_s"] = round(time.time(), 3)
            self.store.save_archive(cid, rec)
        if events.active():
            events.emit("campaign_archive_error", campaign_id=cid,
                        archive_index=index, error=error)

    # --- views: gauges, health summary ---

    @staticmethod
    def _summary_row(camp: dict, records: dict[int, dict]) -> dict:
        counts = {s: 0 for s in _ARCHIVE_GAUGE_STATES}
        for rec in records.values():
            counts[rec["state"]] = counts.get(rec["state"], 0) + 1
        return {
            "id": camp["id"],
            "name": camp.get("name", camp["id"]),
            "state": camp.get("state", "open"),
            "tenant": camp.get("tenant", "default"),
            "created_s": camp.get("created_s", 0.0),
            "finished_s": camp.get("finished_s", 0.0),
            "archives": {"total": len(records), **counts},
        }

    def summary(self) -> dict:
        """The /healthz + fleet_top view: open-campaign count, aggregate
        archive states, and per-campaign rows (most recent first)."""
        with self._lock:
            rows = [(dict(c), dict(self._archives.get(cid, {})))
                    for cid, c in self._campaigns.items()]
        states = {s: 0 for s in _ARCHIVE_GAUGE_STATES}
        campaigns = []
        for camp, records in rows:
            row = self._summary_row(camp, records)
            row["device_s"] = rollup.fold_cost(
                list(records.values()))["device_s"]
            campaigns.append(row)
            if camp.get("state") == "open":
                for s in states:
                    states[s] += row["archives"][s]
        campaigns.sort(key=lambda r: r["id"], reverse=True)
        return {
            "open": sum(1 for c, _r in rows if c.get("state") == "open"),
            "archives": states,
            "campaigns": campaigns[:16],
        }

    def gauge_families(self) -> dict[str, dict[tuple, float]]:
        """``ict_campaign_*`` gauge families, rebuilt whole each tick
        (the replace_gauge_family discipline).  The unlabeled aggregate
        samples are ALWAYS present — zero-valued with no campaigns — so
        the documented families stay live on every exposition
        (tests/test_metric_docs.py)."""
        with self._lock:
            rows = [(dict(c), [dict(r) for r in
                               self._archives.get(cid, {}).values()])
                    for cid, c in self._campaigns.items()]
        archives = {(("state", s),): 0.0 for s in _ARCHIVE_GAUGE_STATES}
        device: dict[tuple, float] = {(): 0.0}
        avoided: dict[tuple, float] = {(): 0.0}
        open_n = 0
        for camp, records in rows:
            if camp.get("state") == "open":
                open_n += 1
                for rec in records:
                    key = (("state", rec["state"]),)
                    archives[key] = archives.get(key, 0.0) + 1.0
            cost = rollup.fold_cost(records)
            if cost["jobs_costed"]:
                cid = camp["id"]
                device[(("campaign", cid),)] = cost["device_s"]
                avoided[(("campaign", cid),)] = cost["avoided_device_s"]
                device[()] += cost["device_s"]
                avoided[()] += cost["avoided_device_s"]
        return {
            "campaign_open": {(): float(open_n)},
            "campaign_archives": archives,
            "campaign_device_seconds": device,
            "campaign_cache_avoided_seconds": avoided,
        }

    def _trim(self) -> None:
        """Drop the oldest terminal campaigns from MEMORY beyond
        KEEP_TERMINAL (ids are time-sortable); the spool keeps them all,
        and GET /campaigns/<id> falls back to it for trimmed ids."""
        with self._lock:
            terminal = sorted(cid for cid, c in self._campaigns.items()
                              if c.get("state") in CAMPAIGN_TERMINAL)
            for cid in terminal[:max(len(terminal) - KEEP_TERMINAL, 0)]:
                del self._campaigns[cid]
                self._archives.pop(cid, None)
