"""Survey-campaign orchestration (ISSUE 16): thousands of archives as
one resumable, work-stealing, cost-accounted run.

A campaign is a JSON manifest (archive list/globs, tenant, optional
per-archive overrides) compiled into per-archive work items, each
submitted through the fleet router's ranked placement path under a
deterministic campaign-scoped idempotency key — so restart-resume and
failover stay exactly-once by construction, and duplicate archives
resolve born-terminal out of the fleet result cache.  The pieces:

- :mod:`.manifest` — the manifest grammar, validation/compilation, and
  the deterministic per-archive idempotency keys;
- :mod:`.store` — the spool-persisted campaign state machine
  (``<spool>/campaigns/<id>/``, .part-rename atomic, restart rehydrates);
- :mod:`.orchestrator` — the router-driven tick: submit pending
  archives, observe placements, fold terminal results, finish campaigns;
- :mod:`.rollup` — the cross-archive QA roll-up and cost showback folds
  served on ``GET /campaigns/<id>``;
- :mod:`.cli` — the ``ict-clean campaign MANIFEST`` follow client.

Full grammar, API, and resume semantics: docs/SERVING.md "Campaigns".
"""

from iterative_cleaner_tpu.campaign.manifest import (  # noqa: F401
    archive_idem_key,
    compile_manifest,
    new_campaign_id,
)
