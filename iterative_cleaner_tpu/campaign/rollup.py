"""Cross-archive QA roll-up and cost showback folds.

Pure functions over the per-archive status records the store holds —
computed on demand for ``GET /campaigns/<id>`` (records are in hand, the
folds are O(archives) dict arithmetic), never cached, so the view can
not drift from the spool.

The QA fold aggregates the per-job :func:`..obs.quality.quality_summary`
dicts: zap-fraction distribution (histogrammed over the shared
FRACTION_BOUNDS layout, so cross-archive aggregation is addition —
the obs/quality rationale), element-wise summed channel/subint occupancy
histograms, the termination-reason mix, and flagged outlier archives.
The cost fold sums the per-job CostRecords (obs/costs.py) the replicas
stamped on the manifests: attributed device-seconds, compile seconds,
cache-avoided seconds, best roofline attainment — the same records the
fleet cost plane federates, so the campaign's summed device-seconds
reconcile with ``GET /fleet/costs`` by construction.
"""

from __future__ import annotations

import statistics

from iterative_cleaner_tpu.obs.quality import FRACTION_BOUNDS

#: An archive is flagged as a zap-fraction outlier when it deviates from
#: the campaign median by more than max(this floor, 3 sigma) — the floor
#: keeps a tightly-clustered campaign from flagging ulp-level scatter.
OUTLIER_FLOOR = 0.05

#: Minimum quality-bearing archives before deviation flagging engages
#: (a median over 2 points flags everything or nothing, uselessly).
OUTLIER_MIN_JOBS = 5

#: Absolute zap fraction above which an archive is always flagged,
#: whatever the campaign's spread — 90%+ zapped data is ruined science
#: regardless of how uniformly ruined its neighbours are.
ZAP_FRAC_HIGH = 0.9


def fold_quality(records: list[dict]) -> dict:
    """The campaign QA roll-up from per-archive status records (only
    ``done`` archives carry quality; the counts make any gap visible)."""
    done = [r for r in records if r.get("state") == "done"]
    with_q = [(r, r.get("quality") or {}) for r in done
              if isinstance(r.get("quality"), dict) and r.get("quality")]
    zaps = [(r, float(q["zap_frac"])) for r, q in with_q
            if "zap_frac" in q]
    bounds = list(FRACTION_BOUNDS)
    zap_hist = [sum(1 for _r, z in zaps if z <= b) for b in bounds]
    chan_hist = [0] * len(bounds)
    sub_hist = [0] * len(bounds)
    chans_full = subs_full = 0
    termination: dict[str, int] = {}
    for r, q in with_q:
        # Element-wise histogram sums only make sense on the one shared
        # bucket layout; a record from a different-era replica keeps its
        # counts out of the fold rather than corrupting it.
        if list(q.get("occupancy_bounds", bounds)) == bounds:
            for i, n in enumerate(q.get("channel_occupancy_hist")
                                  or []):
                if i < len(bounds):
                    chan_hist[i] += int(n)
            for i, n in enumerate(q.get("subint_occupancy_hist") or []):
                if i < len(bounds):
                    sub_hist[i] += int(n)
        chans_full += int(q.get("channels_fully_zapped", 0))
        subs_full += int(q.get("subints_fully_zapped", 0))
        reason = str(q.get("termination", "")
                     or r.get("termination", "") or "")
        if reason:
            termination[reason] = termination.get(reason, 0) + 1
    outliers = _flag_outliers(zaps)
    values = [z for _r, z in zaps]
    return {
        "jobs": len(done),
        "with_quality": len(with_q),
        "zap_frac": {
            "mean": (round(sum(values) / len(values), 6)
                     if values else None),
            "min": round(min(values), 6) if values else None,
            "max": round(max(values), 6) if values else None,
            "bounds": bounds,
            "hist": zap_hist,
        },
        "channel_occupancy_hist": chan_hist,
        "subint_occupancy_hist": sub_hist,
        "channels_fully_zapped": chans_full,
        "subints_fully_zapped": subs_full,
        "termination": {k: termination[k] for k in sorted(termination)},
        "outliers": outliers,
    }


def _flag_outliers(zaps: list[tuple[dict, float]]) -> list[dict]:
    """Flagged archives: always at ZAP_FRAC_HIGH, plus median-deviation
    flags once the campaign has enough quality-bearing archives for the
    spread to mean anything."""
    flagged: dict[int, dict] = {}

    def flag(r: dict, z: float, reason: str) -> None:
        idx = int(r.get("index", -1))
        rec = flagged.setdefault(idx, {
            "index": idx, "path": r.get("path", ""),
            "zap_frac": round(z, 6), "reasons": []})
        rec["reasons"].append(reason)

    for r, z in zaps:
        if z >= ZAP_FRAC_HIGH:
            flag(r, z, "zap_frac_high")
    if len(zaps) >= OUTLIER_MIN_JOBS:
        values = [z for _r, z in zaps]
        median = statistics.median(values)
        spread = max(3.0 * statistics.pstdev(values), OUTLIER_FLOOR)
        for r, z in zaps:
            if abs(z - median) > spread:
                flag(r, z, "zap_frac_deviates")
    return [flagged[i] for i in sorted(flagged)]


def fold_cost(records: list[dict]) -> dict:
    """The campaign cost showback from the per-job CostRecords riding
    the archive status records.  Cache hits (fleet-tier born-terminal
    and replica-tier) show up as avoided seconds, the dedupe dividend."""
    out = {
        "jobs_costed": 0,
        "device_s": 0.0,
        "phase_s": 0.0,
        "compile_s": 0.0,
        "avoided_device_s": 0.0,
        "cache_hits": 0,
        "attainment": None,
    }
    for r in records:
        cost = r.get("cost")
        if not isinstance(cost, dict) or not cost:
            continue
        out["jobs_costed"] += 1
        out["device_s"] += float(cost.get("device_s", 0.0) or 0.0)
        # Total attributed wall seconds across every phase the replica
        # booked (dispatch, oracle, emit, ...): the oracle route runs on
        # the host and books NO device seconds, so phase_s is the figure
        # that stays meaningful whatever backend served the campaign.
        phases = cost.get("phases")
        if isinstance(phases, dict):
            out["phase_s"] += sum(float(v or 0.0)
                                  for v in phases.values())
        out["compile_s"] += float(cost.get("compile_s", 0.0) or 0.0)
        out["avoided_device_s"] += float(
            cost.get("avoided_device_s", 0.0) or 0.0)
        if cost.get("cache_hit"):
            out["cache_hits"] += 1
        att = cost.get("attainment")
        if isinstance(att, (int, float)) and (
                out["attainment"] is None or att > out["attainment"]):
            out["attainment"] = float(att)
    for key in ("device_s", "phase_s", "compile_s", "avoided_device_s"):
        out[key] = round(out[key], 6)
    return out
