"""Full-jitter retry backoff, shared by every retry ladder in the tree.

The worker's original ladder slept a deterministic ``base * 2**attempt``
— fine for one daemon, wrong for a fleet: N replicas (or the router's N
queued failovers) recovering from the same incident all wake on the same
schedule and thundering-herd the spool / the revived replica.  Full
jitter (sleep ``uniform(0, min(cap, base * 2**attempt))``) decorrelates
the retriers while keeping the same expected growth.

Determinism for tests: every caller owns a :class:`random.Random` built
by :func:`make_rng` — seeded from ``ICT_BACKOFF_SEED`` when set (the
test hook; the fleet tests pin it so retry schedules replay exactly),
OS entropy otherwise.  Mask-path modules never import this (delays are
telemetry-visible wall-clock, never mask-affecting).
"""

from __future__ import annotations

import os
import random
import sys

#: Never sleep longer than this between retries, whatever the attempt
#: count — a ladder that backs off past tens of seconds has effectively
#: given up without saying so.
DEFAULT_CAP_S = 30.0


def make_rng(seed: int | None = None) -> random.Random:
    """A private RNG for one retry ladder.  ``seed`` wins; else
    ``ICT_BACKOFF_SEED`` (the deterministic test hook); else OS entropy.
    Private per caller so two ladders never interleave draws — the
    seeded schedule a test pins must not depend on thread timing."""
    if seed is None:
        env = os.environ.get("ICT_BACKOFF_SEED")
        if env is not None:
            try:
                seed = int(env)
            except ValueError:
                print(f"warning: ignoring unparseable ICT_BACKOFF_SEED="
                      f"{env!r} (want an int)", file=sys.stderr)
    return random.Random(seed)


def full_jitter(base_s: float, attempt: int, cap_s: float = DEFAULT_CAP_S,
                rng: random.Random | None = None) -> float:
    """Delay before retry number ``attempt`` (0-based: the first retry
    draws from ``[0, base_s]``).  Bounded above by ``cap_s``; the 2**62
    clamp keeps a runaway attempt counter from overflowing the float."""
    span = min(float(cap_s),
               float(base_s) * float(2 ** min(max(int(attempt), 0), 62)))
    return (rng or random).uniform(0.0, span)
