"""Zap-plot output (reference iterative_cleaner.py:164-170).

Host-side matplotlib on the fetched-back test results; import is deferred so
the framework runs headless without matplotlib installed.
"""

from __future__ import annotations

import numpy as np


def save_zap_plot(
    test_results: np.ndarray,
    ar_name: str,
    chanthresh: float,
    subintthresh: float,
    out_path: str | None = None,
) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.cm as cm
    import matplotlib.pyplot as plt

    if out_path is None:
        # Reference filename: <name>_<chanthresh>_<subintthresh>.png (:169).
        out_path = "%s_%s_%s.png" % (ar_name, chanthresh, subintthresh)
    fig = plt.figure()
    plt.imshow(
        test_results.T,
        vmin=0.999,
        vmax=1.001,
        aspect="auto",
        interpolation="nearest",
        cmap=cm.coolwarm,
    )
    plt.gca().invert_yaxis()
    plt.title("%s cthresh=%s sthresh=%s" % (ar_name, chanthresh, subintthresh))
    plt.savefig(out_path, bbox_inches="tight")
    plt.close(fig)
    return out_path
