"""Compatibility shim: the telemetry layer grew into
:mod:`iterative_cleaner_tpu.obs` (trace context, histograms, Prometheus
exposition, convergence forensics — see docs/OBSERVABILITY.md).  This
module re-exports the same process-global registry, so every existing
``from iterative_cleaner_tpu.utils import tracing`` call site keeps
accounting into the one place the daemon's ``/metrics`` reports."""

from iterative_cleaner_tpu.obs.tracing import (  # noqa: F401
    HIST_BOUNDS,
    StepTimer,
    compile_scope,
    count,
    count_labeled,
    counters_snapshot,
    delta,
    histograms_snapshot,
    install_compile_listener,
    labeled_snapshot,
    observe_phase,
    phase,
    profile_trace,
    reset_counters,
    shape_bucket_label,
    snapshot,
)
