"""Profiling / tracing hooks (SURVEY.md §5: the reference has none; the TPU
framework exposes jax.profiler traces plus per-iteration host timings)."""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """jax.profiler trace around a block when trace_dir is set (view with
    tensorboard or xprof); no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


class StepTimer:
    """Wall-clock per iteration, reported through the progress callback.
    perf_counter: monotonic (no negative laps on wall-clock steps) and
    high-resolution (no 0.0 laps on coarse system clocks)."""

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self.durations: list[float] = []

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        self.durations.append(dt)
        return dt
