"""Profiling / tracing hooks (SURVEY.md §5: the reference has none; the TPU
framework exposes jax.profiler traces plus per-iteration host timings) —
plus the process-global phase counters the serving daemon's ``/metrics``
endpoint reports (service/api.py)."""

from __future__ import annotations

import contextlib
import threading
import time


@contextlib.contextmanager
def profile_trace(trace_dir: str | None):
    """jax.profiler trace around a block when trace_dir is set (view with
    tensorboard or xprof); no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


# --- per-phase counters (the serving daemon's /metrics source) ---
#
# A deliberately tiny metrics registry: monotonic floats keyed by name,
# process-global so every layer (driver, batch dispatch, service worker)
# can account into one place without plumbing a registry object through
# call signatures.  ``observe_phase`` follows the Prometheus summary
# convention (``<name>_s`` total seconds + ``<name>_n`` count), which is
# what the per-stage accounting of astronomical pipelines needs
# ("Pipeline Collector", arXiv:1807.05733): mean stage latency is
# ``load_s / load_n`` with no histogram machinery.

_counters: dict[str, float] = {}
_counters_lock = threading.Lock()


def count(name: str, inc: float = 1.0) -> None:
    """Add ``inc`` to the process-global counter ``name``."""
    with _counters_lock:
        _counters[name] = _counters.get(name, 0.0) + inc


def observe_phase(name: str, seconds: float) -> None:
    """Record one completed phase: total seconds + occurrence count + the
    worst single occurrence (``<name>_max_s``) — the summary pair gives the
    mean, but a latency contract (the online path's per-block alert bound)
    is about the tail, and max is the cheapest tail statistic that needs no
    histogram state."""
    with _counters_lock:
        _counters[f"{name}_s"] = _counters.get(f"{name}_s", 0.0) + seconds
        _counters[f"{name}_n"] = _counters.get(f"{name}_n", 0.0) + 1.0
        key = f"{name}_max_s"
        if seconds > _counters.get(key, 0.0):
            _counters[key] = seconds


@contextlib.contextmanager
def phase(name: str):
    """Time a block into :func:`observe_phase` (exceptions still count —
    a failing load is still a load the operator wants in the latency
    accounting)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        observe_phase(name, time.perf_counter() - t0)


def counters_snapshot() -> dict[str, float]:
    """Point-in-time copy of every counter, sorted by name (stable JSON)."""
    with _counters_lock:
        return dict(sorted(_counters.items()))


def snapshot(prefix: str = "") -> dict[str, float]:
    """:func:`counters_snapshot`, optionally filtered to one subsystem's
    ``prefix`` — the before/after idiom tests use so counter state from one
    case never bleeds into another's assertions (delta = snapshot() minus an
    earlier snapshot(), no global reset needed mid-process)."""
    snap = counters_snapshot()
    if not prefix:
        return snap
    return {k: v for k, v in snap.items() if k.startswith(prefix)}


def delta(before: dict[str, float], key: str) -> float:
    """Counter movement since a :func:`snapshot`; missing keys read 0."""
    return counters_snapshot().get(key, 0.0) - before.get(key, 0.0)


def reset_counters() -> None:
    """Zero the registry (tests only — production counters are cumulative
    for the life of the process, like any scrape target)."""
    with _counters_lock:
        _counters.clear()


class StepTimer:
    """Wall-clock per iteration, reported through the progress callback.
    perf_counter: monotonic (no negative laps on wall-clock steps) and
    high-resolution (no 0.0 laps on coarse system clocks)."""

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self.durations: list[float] = []

    def lap(self) -> float:
        now = time.perf_counter()
        dt = now - self._t0
        self._t0 = now
        self.durations.append(dt)
        return dt
