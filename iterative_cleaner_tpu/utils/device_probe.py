"""Killable probing of the default JAX backend + CPU pinning — the single
home of the wedged-tunnel recipe (the CLI, bench.py, and __graft_entry__.py
all consume it).

A wedged remote-TPU tunnel makes the FIRST in-process ``jax.devices()`` call
hang process-wide — no exception, no timeout, and a later
``JAX_PLATFORMS=cpu`` env override does not rescue it because the plugin
registration already read the stale config (observed live against the dev
tunnel).  Probing in a subprocess first turns that hang into a timeout the
caller can act on; pinning (env var AND config update, never deregistering
backend factories — that would kill Pallas's "tpu" MLIR platform) makes the
CPU fallback actually stick.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import threading
import time

# Platforms JAX itself provides; anything else in JAX_PLATFORMS is a
# registered plugin (e.g. a tunneled remote device) — the only kind that
# can wedge-hang first init.
_BUILTIN_PLATFORMS = {"", "cpu", "gpu", "cuda", "rocm", "tpu"}


def probe_default_backend(timeout_s: float) -> str:
    """Probe default-backend init in a KILLABLE subprocess.

    Returns "ok", "error" (fast failure — let the real init surface the real
    message in-process), or "hang" (killed at the timeout)."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s)
        return "ok" if out.returncode == 0 else "error"
    except subprocess.TimeoutExpired:
        return "hang"


#: Default seconds before the backend-init watchdog speaks up
#: (``ICT_INIT_TIMEOUT_S`` overrides; <= 0 disables).
DEFAULT_INIT_TIMEOUT_S = 120.0


@contextlib.contextmanager
def init_watchdog(label: str = "jax backend init",
                  timeout_s: float | None = None):
    """Diagnose — don't prevent — the wedged-tunnel first-init hang.

    The killable subprocess probe above is the *prevention*; this is the
    *diagnosis* for every path that still reaches first ``jax.devices()``
    in-process (probe disabled, probe passed but the tunnel wedged right
    after, a non-CLI embedding).  A daemon thread watches the wrapped
    block: if the backend is still not live after ``timeout_s``
    (``ICT_INIT_TIMEOUT_S``, default 120), it logs ONE structured warning
    (JSON on stderr) and drops a flight-recorder event, turning the silent
    process-wide freeze into a diagnosable line.  It keeps polling and
    stays silent if init completes (so wrapping a long compile or clean is
    safe — liveness, not wall-clock, is the trigger); the context exit
    always retires the thread."""
    if timeout_s is None:
        try:
            timeout_s = float(os.environ.get("ICT_INIT_TIMEOUT_S",
                                             DEFAULT_INIT_TIMEOUT_S))
        except ValueError:
            timeout_s = DEFAULT_INIT_TIMEOUT_S
    if timeout_s <= 0 or _backend_liveness() == "live":
        yield
        return
    done = threading.Event()

    def _watch() -> None:
        deadline = time.monotonic() + timeout_s
        while not done.wait(min(timeout_s / 10, 1.0)):
            if _backend_liveness() == "live":
                return
            if time.monotonic() >= deadline:
                break
        else:
            return
        if done.is_set() or _backend_liveness() == "live":
            return
        warning = {
            "event": "backend_init_watchdog",
            "label": label,
            "timeout_s": timeout_s,
            "hint": "first jax.devices() has been blocking longer than "
                    "ICT_INIT_TIMEOUT_S — a wedged device tunnel hangs "
                    "first backend init process-wide (CLAUDE.md quirk); "
                    "set JAX_PLATFORMS=cpu before launch to force the "
                    "CPU fallback",
        }
        print(f"warning: {json.dumps(warning)}", file=sys.stderr)
        try:
            from iterative_cleaner_tpu.obs import flight, tracing

            flight.note("backend_init_watchdog", label=label,
                        timeout_s=timeout_s)
            tracing.count("backend_init_watchdog_fired")
        except Exception:  # noqa: BLE001 — the stderr line already landed
            pass

    th = threading.Thread(target=_watch, daemon=True,
                          name="ict-init-watchdog")
    th.start()
    try:
        yield
    finally:
        done.set()


def pin_cpu_backend() -> None:
    """Pin this process's first backend init to CPU: env (for subprocesses)
    AND config update (beats the plugin registration's stale read).  Leaves a
    process whose backend is already (or possibly) initialized untouched —
    first-init is the only moment that can hang, and retargeting a live
    process would silently move its subsequent dispatches, so anything but a
    definite "not_live" declines to pin (fail closed)."""
    if _backend_liveness() != "not_live":
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass


_MISSING = object()


def _backend_liveness() -> str:
    """Whether this process's JAX backend is already initialized: "live",
    "not_live", or "unknown" (JAX-version attribute drift).  The tri-state
    matters because the two consumers fail in opposite directions: the
    killable subprocess probe is safe to run when liveness is unknown (so
    ensure_responsive_backend skips only on a definite "live" — wedge
    protection survives drift), while pin_cpu_backend must NOT retarget a
    possibly-live process (so it acts only on a definite "not_live").
    Prefers the public-ish ``xla_bridge.backends_are_initialized()``.

    Reads ``sys.modules`` instead of importing: a process that never
    imported jax cannot have a live backend, and an IMPORT here is an
    active hazard — this check runs from watchdog/metrics threads, and a
    ``from jax._src import xla_bridge`` racing another thread's first
    ``import jax`` forms exactly the lock cycle CPython's circular-import
    deadlock avoidance breaks by exposing partially-initialized modules
    (observed killing a fresh daemon's loader pool).  The liveness guard
    exists so observability never touches the backend; that must include
    never *importing* it."""
    try:
        if "jax" not in sys.modules:
            return "not_live"   # jax never imported -> no backend, definite
        _xb = sys.modules.get("jax._src.xla_bridge")
        if _xb is None:
            # jax is imported but the private module path is gone (layout
            # drift — or mid-import in another thread): NOT a definite
            # "not_live"; pin_cpu_backend must never retarget on drift.
            return "unknown"
        fn = getattr(_xb, "backends_are_initialized", None)
        if fn is not None:
            return "live" if fn() else "not_live"
        backends = getattr(_xb, "_backends", _MISSING)
        if backends is _MISSING:
            return "unknown"  # both signals gone
        return "live" if backends else "not_live"
    except Exception:  # noqa: BLE001 — JAX-version drift
        return "unknown"


def _backend_already_live() -> bool:
    """Back-compat boolean view (probe consumer): only a definite "live"
    counts — "unknown" keeps the killable probe running."""
    return _backend_liveness() == "live"


def _remote_platform_in_play() -> bool:
    """Only a registered plugin platform (or the axon pool env) can
    wedge-hang; plain local cpu/gpu/tpu machines skip the probe cost."""
    if os.environ.get("JAX_PLATFORMS", "") not in _BUILTIN_PLATFORMS:
        return True
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def ensure_responsive_backend(timeout_s: float | None = None) -> str:
    """CLI front door: probe before the first real JAX call, demote to CPU
    loudly when the tunnel is wedged (masks are bit-identical on CPU; only
    wall-clock differs).

    Returns "skipped" (no remote platform in play, already pinned to cpu,
    probing disabled via ICT_NO_DEVICE_PROBE=1 / ICT_DEVICE_PROBE_S<=0, or
    a backend is already live), "ok" (probe answered), "demoted" (probe
    hung through two windows; process pinned to CPU), or "demote_failed"
    (probe hung but liveness was undeterminable, so the pin was declined —
    the caller was warned the next JAX call may hang).
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get("ICT_DEVICE_PROBE_S", 120))
    if (os.environ.get("ICT_NO_DEVICE_PROBE") == "1"
            or timeout_s <= 0
            or os.environ.get("JAX_PLATFORMS", "") == "cpu"
            or not _remote_platform_in_play()
            or _backend_already_live()):
        return "skipped"
    # Two windows: a cold-tunnel first init can legitimately be slow once.
    for _ in range(2):
        if probe_default_backend(timeout_s) != "hang":
            return "ok"
    pin_cpu_backend()
    if _backend_liveness() == "unknown":
        # pin_cpu_backend declined (it must not retarget a possibly-live
        # backend), so the demotion did NOT take — say so instead of
        # promising a CPU fallback the next JAX call won't honor.
        print(
            f"warning: the default JAX backend hung through two "
            f"{timeout_s:.0f}s probes (wedged device tunnel?), but backend "
            "liveness is undeterminable under this JAX version so the CPU "
            "fallback was NOT applied — the next JAX call may hang; set "
            "JAX_PLATFORMS=cpu in the environment before launch to force "
            "the fallback",
            file=sys.stderr)
        return "demote_failed"
    print(
        f"warning: the default JAX backend hung through two {timeout_s:.0f}s "
        "probes (wedged device tunnel?); falling back to the CPU backend — "
        "masks are identical, wall-clock is not (set ICT_NO_DEVICE_PROBE=1 "
        "to skip probing)",
        file=sys.stderr)
    return "demoted"
