"""Bounded XLA-executable growth for heterogeneous-shape workloads.

Every distinct cube shape (and sharded-batch size) compiles a fresh set of
XLA executables that JAX caches for the life of the process.  Deep fuzzing
found the accumulation is not harmless: ~70 distinct mixed-shape compiles
into one process segfaulted the virtual-CPU platform deterministically
(tools/fuzz_sweep.py works around it with a periodic ``jax.clear_caches()``).
Real deployments bucket archives by shape (parallel/batch.py) so one process
rarely sees more than a few shapes — but a heterogeneous-directory workload
can approach that regime, so the drivers note each (shape, route fingerprint)
they are about to compile here and the caches are dropped every
``DISTINCT_SHAPE_LIMIT`` distinct keys.  The fingerprint (route name plus the
config axes that compile distinct executable sets: fused/stepwise, x64,
pallas, want_residual) matters because the ~70-compile budget is per compiled
*executable*, not per cube shape — one shape can compile several executable
sets, so a mixed-route workload would exceed the safe cadence well before 20
bare shapes accumulated.  A drop only costs a recompile of whatever runs
next; live device arrays are untouched.
"""

from __future__ import annotations

DISTINCT_SHAPE_LIMIT = 20  # matches the fuzz sweep's empirically safe cadence

_seen: set[tuple] = set()


def note_compiled_shape(key: tuple) -> bool:
    """Record a (shape, route-fingerprint) key about to be jit-compiled; drop
    JAX's compilation caches once ``DISTINCT_SHAPE_LIMIT`` distinct keys
    accumulate.  Returns True when a drop happened (the counter restarts).
    Only call on the JAX path — the numpy backend must stay JAX-import-free."""
    _seen.add(tuple(key))
    if len(_seen) >= DISTINCT_SHAPE_LIMIT:
        import jax

        jax.clear_caches()
        _seen.clear()
        return True
    return False
