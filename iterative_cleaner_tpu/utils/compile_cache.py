"""Bounded XLA-executable growth for heterogeneous-shape workloads.

Every distinct cube shape (and sharded-batch size) compiles a fresh set of
XLA executables that JAX caches for the life of the process.  Deep fuzzing
found the accumulation is not harmless: ~70 distinct mixed-shape compiles
into one process segfaulted the virtual-CPU platform deterministically
(tools/fuzz_sweep.py works around it with a periodic ``jax.clear_caches()``).
Real deployments bucket archives by shape (parallel/batch.py) so one process
rarely sees more than a few shapes — but a heterogeneous-directory workload
can approach that regime, so the drivers note each (shape, route fingerprint)
they are about to compile here and the caches are dropped every
``DISTINCT_SHAPE_LIMIT`` distinct keys.  The fingerprint (route name plus the
config axes that compile distinct executable sets: fused/stepwise, x64,
pallas, want_residual) matters because the ~70-compile budget is per compiled
*executable*, not per cube shape — one shape can compile several executable
sets, so a mixed-route workload would exceed the safe cadence well before 20
bare shapes accumulated.  A drop only costs a recompile of whatever runs
next; live device arrays are untouched.
"""

from __future__ import annotations

DISTINCT_SHAPE_LIMIT = 20  # matches the fuzz sweep's empirically safe cadence

_seen: set[tuple] = set()


def inmemory_route_key(shape, cfg, want_residual: bool) -> tuple:
    """The compile-cache key for the IN-MEMORY route clean_cube will take —
    shared by clean_cube's accounting and the precompile warm path so the
    two can never disagree.  ``cfg`` must be the raw user config: the
    pallas/incremental residual fallbacks are applied here, exactly as
    clean_cube resolves them before keying (pallas through the shared
    tri-state resolver, so the auto default keys the executable that
    actually compiles on this platform)."""
    from iterative_cleaner_tpu.ops.pallas_kernels import resolve_use_pallas

    nsub, nchan, nbin = shape
    pr = tuple(cfg.pulse_region)
    pallas = resolve_use_pallas(cfg, nbin, want_residual)
    incremental = cfg.incremental_template and not want_residual
    if cfg.fused:
        # fused_clean statics: max_iter, pulse_region, want_residual,
        # use_pallas, incremental.
        return (nsub, nchan, nbin, "fused", pallas, cfg.x64,
                want_residual, cfg.max_iter, incremental, pr)
    # clean_step statics are only (pulse_region, use_pallas): the same
    # executable serves residual and non-residual requests.  The
    # incremental route swaps clean_step for the dense/advance/
    # step_from_template executable set.
    return (nsub, nchan, nbin, "stepwise", pallas, cfg.x64, incremental, pr)


def batch_route_key(batch_shape, cfg) -> tuple:
    """The compile-cache key for one sharded BATCH dispatch (directory
    buckets and the serving daemon's shape buckets alike):
    ``batch_shape`` is the stacked (batch, nsub, nchan, nbin).  Mirrors
    batched_fused_clean's static-arg surface (max_iter, pulse_region).  No
    x64 axis: the batch route has no x64 handling (preprocess emits f32 and
    the sharded kernel never casts), so both cfg.x64 values reuse one
    executable.  Shared by parallel/batch._finish_bucket and the service
    warm pool (service/pool.py) so the dispatcher's accounting and the
    warm-skip check can never disagree."""
    return (*batch_shape, "batch", cfg.max_iter, tuple(cfg.pulse_region))


# Size bound for the CLI-default persistent cache (ADVICE r05: the 0-second
# min-compile-time floor serializes every executable, so a long-lived
# heterogeneous workload — and especially the serving daemon — grows the
# directory without bound).  2 GiB holds hundreds of TPU executables; the
# trim is FIFO by mtime, so the oldest-written entries go first.
CACHE_TRIM_DEFAULT_MB = 2048


def trim_persistent_cache(path: str | None = None,
                          max_bytes: int | None = None) -> int:
    """Delete oldest-written entries until the persistent-cache directory is
    under ``max_bytes`` (default ``ICT_COMPILE_CACHE_MAX_MB``, 2048; <= 0
    disables).  Returns bytes removed.  Called on CLI startup and on
    serving-daemon startup — the two places the cache is enabled by
    default; the directory stays user-prunable by hand (documented in
    README).  Best-effort like the cache itself: a vanished file or an
    unreadable directory trims nothing rather than failing the run."""
    import os

    if max_bytes is None:
        env = os.environ.get("ICT_COMPILE_CACHE_MAX_MB",
                             str(CACHE_TRIM_DEFAULT_MB))
        try:
            mb = float(env)
        except ValueError:
            import sys

            print(f"warning: ignoring unparseable ICT_COMPILE_CACHE_MAX_MB"
                  f"={env!r}; using the {CACHE_TRIM_DEFAULT_MB} MB default",
                  file=sys.stderr)
            mb = CACHE_TRIM_DEFAULT_MB
        max_bytes = int(mb * 1e6)
    if max_bytes <= 0:
        return 0
    path = path or _default_cache_dir()
    try:
        entries = []
        for root, _dirs, files in os.walk(path):
            for name in files:
                p = os.path.join(root, name)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, p))
        total = sum(size for _, size, _ in entries)
        removed = 0
        for _mtime, size, p in sorted(entries):
            if total - removed <= max_bytes:
                break
            try:
                os.remove(p)
                removed += size
            except OSError:
                continue
        if removed:
            from iterative_cleaner_tpu.obs import tracing

            tracing.count("compile_cache_trim_bytes", float(removed))
        return removed
    except Exception:  # noqa: BLE001 — trimming is opportunistic
        return 0


def _default_cache_dir() -> str:
    import os

    return os.path.join(os.path.expanduser("~"), ".cache",
                        "iterative_cleaner_tpu", "xla")


def enable_and_trim_persistent_cache() -> str | None:
    """The CLI-layer policy in one place (cli.main and the ict-serve
    daemon both apply it): enable the persistent cache, then size-bound it
    — but ONLY when the directory in effect is the tool-owned default.  An
    explicit JAX_COMPILATION_CACHE_DIR may be a cache shared with other
    JAX workloads, and deleting their 20-40 s TPU compiles to enforce our
    bound is not this tool's call (the dir is 'used as-is', eviction
    included).  Returns the directory in effect, or None when
    disabled/failed."""
    path = enable_persistent_cache()
    if path and path == _default_cache_dir():
        trim_persistent_cache(path)
    return path


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point XLA's persistent compilation cache at a writable directory so
    *separate processes* skip recompiling identical kernels — a cold CLI
    run on a shape any earlier run compiled starts in ~the dispatch time
    instead of the 20-40 s TPU compile, and the hardware playbook's bench
    runs stop paying the probe run's compiles inside a scarce tunnel
    window.  (In-process executable reuse is a different mechanism — the
    jit cache above; this survives the process.)

    Call before the first backend use.  Precedence: ICT_NO_COMPILE_CACHE=1
    disables; an explicit JAX_COMPILATION_CACHE_DIR (or an explicit
    ``path``) is used as-is; otherwise ~/.cache/iterative_cleaner_tpu/xla.
    Best-effort by design — an unwritable directory or an unsupported
    backend just means compilation stays uncached.  Returns the directory
    in effect, or None when disabled/failed.
    """
    import os

    if os.environ.get("ICT_NO_COMPILE_CACHE") == "1":
        return None
    path = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or _default_cache_dir())
    try:
        os.makedirs(path, exist_ok=True)
        import jax

        # Reset FIRST, configure second: jax memoizes "cache unused" at
        # the first compile of the process (_cache_checked/_cache_used in
        # jax._src.compilation_cache), so enabling the dir after any
        # compile would be silently ignored without a reset — and doing
        # the reset before the config updates means a version-drift
        # failure at ANY step leaves the cache fully off, keeping the
        # None return honest (configure-then-reset could enable caching
        # and then report it disabled).  No compile runs in between, so
        # the order is otherwise equivalent.
        if not _reset_cache_state():
            return None
        # Cache every compile: the kernels worth caching here are either
        # trivially cheap to serialize (CPU) or exactly the 20-40 s TPU
        # compiles the default 1 s floor would admit anyway — and the
        # bench/CLI cold numbers should not depend on a heuristic floor.
        # The floor still precedes the dir (the dir update is what
        # activates caching).
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_compilation_cache_dir", path)
        return path
    except Exception:  # noqa: BLE001 — caching is opportunistic
        return None


def _reset_cache_state() -> bool:
    """Drop jax's memoized persistent-cache object and used-state (the one
    place that touches the private API); returns False if the private
    surface drifted.  Shared by enable_persistent_cache and the test
    teardown that must not leave a stale cache object pointed at a
    deleted directory."""
    try:
        from jax._src.compilation_cache import reset_cache

        reset_cache()
        return True
    except Exception:  # noqa: BLE001 — private-API drift tolerated
        return False


def already_noted(key: tuple) -> bool:
    """Whether this exact key was noted since the last cache drop — i.e.
    its executables are (or are being) compiled in this process.  The warm
    path uses it to skip redundant dummy runs for same-shape archives."""
    return tuple(key) in _seen


def forget_noted(key: tuple) -> None:
    """Withdraw a key that was noted optimistically before a compile that
    then FAILED (the service warm pool's per-size accounting): leaving it
    would make already_noted report an executable that was never built, so
    the real dispatch would skip a warm it still needs."""
    _seen.discard(tuple(key))


def _shape_bucket_of(key: tuple) -> str:
    """The leading integer dims of a route key, as the telemetry shape
    bucket label ('8x16x64' — batch keys include the batch axis)."""
    dims = []
    for v in key:
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            dims.append(str(int(v)))
        else:
            break
    return "x".join(dims) or "scalar"


def note_compiled_shape(key: tuple) -> bool:
    """Record a (shape, route-fingerprint) key about to be jit-compiled; drop
    JAX's compilation caches once ``DISTINCT_SHAPE_LIMIT`` distinct keys
    accumulate.  Returns True when a drop happened (the counter restarts).
    Only call on the JAX path — the numpy backend must stay JAX-import-free.

    Also the in-process executable cache's accounting hook (obs layer): a
    re-noted key means the executable set is already live or in flight (a
    cache *hit* — no NEW compile attributable to this caller; the warm
    paths note before compiling, so a warmed shape's real dispatch counts
    as a hit by design), a fresh key means compiles are coming (a *miss*);
    both land in the process-global counters the daemon's ``/metrics``
    reports, the misses per shape bucket.  Real backend compiles are
    accounted separately (``jax_compile_s/_n``, obs.tracing's monitoring
    listener) — compare the two to see warm-path effectiveness."""
    from iterative_cleaner_tpu.obs import tracing

    key = tuple(key)
    if key in _seen:
        tracing.count("compile_cache_key_hits")
        return False
    tracing.count("compile_cache_key_misses")
    tracing.count_labeled("compile_keys_total",
                          {"shape_bucket": _shape_bucket_of(key)})
    _seen.add(key)
    if len(_seen) >= DISTINCT_SHAPE_LIMIT:
        import jax

        jax.clear_caches()
        _seen.clear()
        tracing.count("compile_cache_drops")
        return True
    return False
